"""Sim-backend instrumentation: stride-sampled, deferred-sync metrics.

The sim hot loop must never pay a device->host sync for telemetry (one
sync per round erases the batching the backend exists for). The contract
here:

- ``due(tick)`` decides on the host, from tick arithmetic alone, whether
  this chunk boundary is a sample point (every ``stride`` rounds).
- ``record(tick, sample)`` accepts the sample's metrics as *device
  scalars* (or host floats, for the native host path) and buffers them.
  Nothing is converted, so jit dispatch stays asynchronous.
- ``flush()`` converts everything buffered in one go (a single sync at
  the end of a run / on demand), pushes the latest values into the
  registry gauges, emits one ``sim_round`` trace event per sample, and
  returns the series as plain dicts.

Wall-clock: ``record`` stamps ``perf_counter`` at dispatch time, so the
per-round wall time derived between consecutive samples measures the
async dispatch cadence; over a steady run backpressure makes it converge
on true device-step time (the same reasoning the bench's best-of-N trial
loop uses). docs/observability.md spells this out.
"""

from __future__ import annotations

import time

from .registry import MetricsRegistry
from .trace import TraceWriter

# Gauge/counter names shared by both sim engines, labelled by engine
# ("xla", "host-native") so a process driving both stays legible.
# Percentiles the sim staleness tensor is compressed to — THE single
# source for both the sampler keys (``staleness_p<label>``, computed on
# device by ops.gossip.staleness_percentiles, which imports this) and
# the ``aiocluster_sim_staleness_rounds{pct=}`` gauge export below.
# "100" is the max — version_spread in round units.
STALENESS_PCTS = (("50", 0.50), ("99", 0.99), ("100", 1.0))

_SAMPLE_GAUGES = (
    ("aiocluster_sim_tick", "Current simulated gossip round"),
    ("aiocluster_sim_mean_fraction", "Mean replicated fraction over alive pairs"),
    ("aiocluster_sim_min_fraction", "Worst replicated fraction over alive pairs"),
    ("aiocluster_sim_converged_owners", "Owners fully replicated to all alive nodes"),
    ("aiocluster_sim_alive_nodes", "Nodes currently alive in the simulation"),
    ("aiocluster_sim_version_spread", "Worst key-version lag over alive pairs"),
    (
        "aiocluster_sim_fd_false_positive_fraction",
        "Alive off-diagonal pairs the observer believes dead "
        "(FD liveness quality; present when the FD is tracked)",
    ),
)


class SimMetrics:
    """Stride sampler + registry/trace bridge for one sim run."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        trace: TraceWriter | None = None,
        stride: int = 64,
        engine: str = "xla",
        bytes_per_kv: float = 35.0,
        start_tick: int = 0,
        writes_per_round: int = 0,
    ) -> None:
        if stride < 1:
            raise ValueError("metrics stride must be >= 1")
        # No registry -> a PRIVATE one (trace-only runs), never the
        # process default: a sim study must not inject stale series into
        # a registry some other component serves over /metrics.
        self.registry = registry if registry is not None else MetricsRegistry()
        self.trace = trace
        self.stride = stride
        self.engine = engine
        # Wire cost of one replicated key-version for the delta-bytes
        # ESTIMATE (default: the bench workload's 8-byte keys/values
        # under the proto3 framing of wire/sizes.py).
        self.bytes_per_kv = bytes_per_kv
        self._gauges = {
            name: self.registry.gauge(name, help_text, labels=("engine",))
            .labels(engine)
            for name, help_text in _SAMPLE_GAUGES
        }
        self._rounds = self.registry.counter(
            "aiocluster_sim_rounds_total",
            "Simulated gossip rounds advanced",
            labels=("engine",),
        ).labels(engine)
        self._step_seconds = self.registry.histogram(
            "aiocluster_sim_step_seconds",
            "Per-round wall time, derived between metric samples",
            labels=("engine",),
            buckets=(1e-5, 1e-4, 1e-3, 0.01, 0.1, 1.0, 10.0),
        ).labels(engine)
        self._delta_kvs = self.registry.counter(
            "aiocluster_sim_delta_key_versions_total",
            "Key-versions replicated by gossip (sampled between windows)",
            labels=("engine",),
        ).labels(engine)
        self._delta_bytes = self.registry.counter(
            "aiocluster_sim_delta_bytes_total",
            "Estimated delta bytes moved (key-versions x wire cost)",
            labels=("engine",),
        ).labels(engine)
        self._chunk_cache = self.registry.gauge(
            "aiocluster_sim_chunk_cache_size",
            "Compiled chunk callables currently cached by the driver "
            "(bounded; sim/simulator.py BoundedFnCache)",
            labels=("engine",),
        ).labels(engine)
        # Staleness normalization: the staleness tensor counts
        # key-versions behind; at a steady write rate of w versions per
        # owner per round, lag/w IS rounds-behind (w <= 1, including
        # the pure-convergence study's w = 0, leaves the raw lag —
        # versions are rounds there). Kept as a host-side divide at
        # flush so the device/oracle parity stays on exact integers.
        self._staleness_scale = max(int(writes_per_round), 1)
        self._staleness = self.registry.gauge(
            "aiocluster_sim_staleness_rounds",
            "Fleet staleness distribution: per-node rounds-behind-"
            "owner-max-version (the staleness tensor's nearest-rank "
            "percentile; pct=100 is the max — version_spread in round "
            "units)",
            labels=("engine", "pct"),
        )
        self._state_bytes = self.registry.gauge(
            "aiocluster_sim_state_bytes",
            "Planned resident SimState bytes for this run's memory-"
            "ladder rung (sim.memory.plan; set once at construction)",
            labels=("engine",),
        ).labels(engine)
        self._pallas_fallbacks = self.registry.gauge(
            "aiocluster_sim_pallas_fallbacks",
            "Traced configs that WANTED the Pallas kernels but degraded "
            "to XLA, by reason — the DELTAS accrued on the trace-time "
            "ledger (ops.gossip.pallas_fallbacks) since this sampler was "
            "constructed, exported at flush; deltas rather than the raw "
            "process-wide counts, so a scoped test "
            "(pallas_fallbacks_scope) or an earlier run in the process "
            "cannot masquerade as THIS run's degradation; deliberately "
            "NOT engine-labelled",
            labels=("reason",),
        )
        # Baseline for the delta export: the STABLE process-wide view
        # (scope-parked counts included — gossip.pallas_fallbacks_total)
        # of the ledger when this run's sampler came up; the raw
        # counter would read zeroed inside a pallas_fallbacks_scope and
        # the scope's exit would then masquerade ambient history as
        # this run's degradation.
        from ..ops.gossip import pallas_fallbacks_total

        self._fallbacks_base: dict[str, int] = dict(pallas_fallbacks_total())
        self._pending: list[tuple[int, float, dict]] = []
        # Rounds run before the sampler existed (a resumed checkpoint's
        # tick) must not inflate the rounds counter at the first sample.
        self._start_tick = start_tick
        self._last_tick: int | None = None
        self._last_wall: float | None = None
        self.samples: list[dict] = []

    @property
    def last_tick(self) -> int | None:
        """Tick of the most recent sample (None before the first) — the
        drivers use it to close the series at the run's final state."""
        return self._last_tick

    def set_chunk_cache_size(self, n: int) -> None:
        """Driver hook: current compiled-chunk cache entry count (pure
        host bookkeeping — no device traffic)."""
        self._chunk_cache.set(n)

    def set_state_bytes(self, n: int) -> None:
        """Driver hook: the run's planned resident state bytes (the
        memory ladder's figure for this rung — host arithmetic only)."""
        self._state_bytes.set(n)

    def _export_pallas_fallbacks(self) -> None:
        """Mirror the trace-time loud-fallback ledger into labeled
        gauges so kernel degradation shows up on /metrics, not only in
        test assertions. Exports DELTAS of the stable scope-inclusive
        view (gossip.pallas_fallbacks_total — invariant across
        pallas_fallbacks_scope entry/exit, so neither a mid-scope flush
        nor a sampler constructed inside a scope can misattribute
        ambient history; max(0) is a belt against direct counter
        surgery) against the construction-time snapshot — the gauge
        answers "did THIS run degrade", not "did anything in the
        process ever degrade". The ledger is process-global (one count
        per compiled config, whichever engine traced it), so the gauge
        carries only the reason label."""
        from ..ops.gossip import pallas_fallbacks_total

        for reason, count in pallas_fallbacks_total().items():
            delta = count - self._fallbacks_base.get(reason, 0)
            self._pallas_fallbacks.labels(reason).set(max(delta, 0))

    def due(self, tick: int) -> bool:
        """Host-side stride gate: true when ``tick`` crossed into a new
        stride window since the last sample (chunked steppers land on
        chunk boundaries, so "crossed" rather than "equals a multiple")."""
        if self._last_tick is None:
            return True
        return tick // self.stride > self._last_tick // self.stride

    def record(self, tick: int, sample: dict) -> None:
        """Buffer one sample. ``sample`` values may be device scalars —
        they are NOT converted here."""
        now = time.perf_counter()
        prev = self._start_tick if self._last_tick is None else self._last_tick
        if tick > prev:
            self._rounds.inc(tick - prev)
        self._pending.append((tick, now, dict(sample)))
        self._last_tick = tick
        self._last_wall = now

    def flush(self) -> list[dict]:
        """Convert buffered samples (the one deliberate sync), update
        gauges to the latest values, emit trace events, and return the
        full series accumulated so far."""
        import numpy as np

        prev_tick = prev_wall = None
        if self.samples:
            prev_tick = self.samples[-1]["tick"]
            prev_wall = self.samples[-1]["_wall"]
        prev_kv = None
        if self.samples:
            prev_kv = self.samples[-1].get("kv_known")
        for tick, wall, raw in self._pending:
            sample = {"tick": int(tick), "_wall": wall}
            for key, value in raw.items():
                sample[key] = float(np.asarray(value))
            if prev_tick is not None and tick > prev_tick:
                per_round = (wall - prev_wall) / (tick - prev_tick)
                sample["step_seconds"] = round(per_round, 9)
                self._step_seconds.observe(per_round)
            kv = sample.get("kv_known")
            if kv is not None and prev_kv is not None:
                moved = max(kv - prev_kv, 0.0)
                sample["delta_key_versions"] = moved
                sample["delta_bytes_est"] = round(moved * self.bytes_per_kv)
                self._delta_kvs.inc(moved)
                self._delta_bytes.inc(moved * self.bytes_per_kv)
            prev_kv = kv if kv is not None else prev_kv
            prev_tick, prev_wall = tick, wall
            self.samples.append(sample)
            if self.trace is not None:
                self.trace.emit(
                    "sim_round",
                    engine=self.engine,
                    **{k: v for k, v in sample.items() if k != "_wall"},
                )
        self._pending.clear()
        if self.samples:
            last = self.samples[-1]
            for short, gauge in (
                ("tick", "aiocluster_sim_tick"),
                ("mean_fraction", "aiocluster_sim_mean_fraction"),
                ("min_fraction", "aiocluster_sim_min_fraction"),
                ("converged_owners", "aiocluster_sim_converged_owners"),
                ("alive_count", "aiocluster_sim_alive_nodes"),
                ("version_spread", "aiocluster_sim_version_spread"),
                (
                    "fd_false_positive_fraction",
                    "aiocluster_sim_fd_false_positive_fraction",
                ),
            ):
                if short in last:
                    self._gauges[gauge].set(last[short])
            for pct, _ in STALENESS_PCTS:
                key = f"staleness_p{pct}"
                if key in last:
                    self._staleness.labels(self.engine, pct).set(
                        last[key] / self._staleness_scale
                    )
        self._export_pallas_fallbacks()
        return [
            {k: v for k, v in s.items() if k != "_wall"} for s in self.samples
        ]


def marked_write_state(cfg, owner: int = 0):
    """A fully converged fleet the instant after ``owner`` published ONE
    new version — the sim-side analogue of the propagation benchmark's
    marked write on a settled loopback fleet (docs/observability.md
    "Propagation & provenance").

    Built from ``init_state`` (heartbeats/FD fields at their boot
    values) with the watermark matrix overridden to full convergence at
    the old versions and ``max_version[owner]`` bumped by one. Supports
    every rung: the packed u4 residual form is residual 0 everywhere
    except the owner's column (one version behind off-diagonal)."""
    import jax.numpy as jnp

    from ..sim.packed import pack_u4
    from ..sim.state import VERSION_LIMITS, init_state

    n = cfg.n_nodes
    if not 0 <= owner < n:
        raise ValueError(f"owner {owner} outside [0, {n})")
    keys = cfg.keys_per_node
    if keys + 1 >= VERSION_LIMITS[cfg.version_dtype]:
        raise ValueError(
            f"marked write would overflow version_dtype="
            f"{cfg.version_dtype} (keys_per_node={keys})"
        )
    state = init_state(cfg)
    mv = jnp.full((n,), keys, jnp.int32).at[owner].add(1)
    if cfg.version_dtype == "u4r":
        # Residual space: converged = 0; the marked write leaves every
        # non-owner observer exactly one version behind the owner.
        col = jnp.arange(n)[None, :] == owner
        row = jnp.arange(n)[:, None] == owner
        w = pack_u4(jnp.where(col & ~row, 1, 0))
    else:
        w = jnp.full((n, n), keys, jnp.dtype(cfg.version_dtype))
        w = w.at[owner, owner].set(keys + 1)
    return state.replace(w=w, max_version=mv)


def wavefront_series(
    cfg,
    *,
    owner: int = 0,
    seed: int = 0,
    max_rounds: int = 512,
    threshold: float = 0.99,
) -> dict:
    """The marked write's epidemic wavefront: fraction of alive nodes
    that see owner's new version, measured after EVERY round — the
    tensor analogue of the runtime provenance tracer's write→visible
    latencies, letting twin comparisons line up *curves*, not just
    convergence round counts.

    A study helper, not a hot loop: it steps a chunk=1 Simulator and
    syncs one (N,) column per round. Returns ``{"fractions": [...],
    "rounds_to_threshold": r | None, "threshold": t}`` where
    ``fractions[k]`` is visibility after k rounds (``fractions[0]`` is
    the pre-gossip state: just the owner)."""
    import numpy as np

    from ..sim.packed import watermarks_i32
    from ..sim.simulator import Simulator

    if not 0.0 < threshold <= 1.0:
        raise ValueError("threshold must be in (0, 1]")
    state = marked_write_state(cfg, owner)
    sim = Simulator(cfg, seed=seed, chunk=1, state=state)
    target = int(cfg.keys_per_node) + 1

    def fraction() -> float:
        wv = np.asarray(watermarks_i32(sim.state))
        alive = np.asarray(sim.state.alive)
        seen = (wv[:, owner] >= target) & alive
        return float(seen.sum()) / float(max(alive.sum(), 1))

    fractions = [fraction()]
    rounds_to_threshold = None
    for rnd in range(1, max_rounds + 1):
        sim.run(1)
        fractions.append(fraction())
        if fractions[-1] >= threshold:
            rounds_to_threshold = rnd
            break
    return {
        "fractions": fractions,
        "rounds_to_threshold": rounds_to_threshold,
        "threshold": threshold,
    }


class SweepMetrics:
    """Per-lane gauges for one multi-scenario sweep (sim/sweep.py).

    The sweep's hot loop never syncs for telemetry; this bridge is fed
    host-side numpy arrays at result time (ONE conversion of each
    lane-axis array — never a per-lane ``int(x[lane])`` loop, which is
    exactly the pattern the analyzer's ACT023 rule flags)."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        engine: str = "xla",
    ) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self.engine = engine
        self._lanes = self.registry.gauge(
            "aiocluster_sim_sweep_lanes",
            "Scenario lanes in the current sweep",
            labels=("engine",),
        ).labels(engine)
        self._lanes_converged = self.registry.gauge(
            "aiocluster_sim_sweep_lanes_converged",
            "Sweep lanes whose convergence tick has been observed",
            labels=("engine",),
        ).labels(engine)
        self._lane_rounds = self.registry.gauge(
            "aiocluster_sim_lane_rounds_to_convergence",
            "First round at which the lane held full convergence "
            "(absent until observed)",
            labels=("engine", "lane"),
        )
        self._lane_spread = self.registry.gauge(
            "aiocluster_sim_lane_version_spread",
            "Worst key-version lag over alive pairs, per sweep lane",
            labels=("engine", "lane"),
        )

    def update(self, rounds_to_convergence, version_spread=None) -> None:
        """Push per-lane series (host values: lists/np arrays; None or 0
        rounds = lane not converged yet)."""
        rounds = list(rounds_to_convergence)
        self._lanes.set(len(rounds))
        self._lanes_converged.set(sum(1 for r in rounds if r))
        for lane, r in enumerate(rounds):
            if r:
                self._lane_rounds.labels(self.engine, str(lane)).set(float(r))
        if version_spread is not None:
            for lane, s in enumerate(list(version_spread)):
                self._lane_spread.labels(self.engine, str(lane)).set(float(s))
