"""Profiling corner of the obs package (moved from utils/profiling.py).

The two observability seams the tensor backend makes natural: an XLA
profiler trace (view in TensorBoard / xprof) and a tiny wall-clock
section timer for host-side phases. ``utils/profiling.py`` remains as a
compatibility shim re-exporting these names.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@contextmanager
def device_trace(logdir: str):
    """Capture a jax.profiler trace (HLO timelines, per-op device time)
    for everything run inside the block. Works on TPU and CPU."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclass
class SectionTimer:
    """Accumulates wall-clock per named section; ``summary()`` gives
    {name: total_seconds}. The host-side companion to device_trace."""

    totals: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)

    @contextmanager
    def section(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            self.totals[name] = self.totals.get(name, 0.0) + elapsed
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            name: {
                "seconds": round(total, 6),
                "calls": self.counts[name],
                "mean_seconds": round(total / self.counts[name], 6),
            }
            for name, total in self.totals.items()
        }
