"""Propagation provenance: join per-node traces into epidemic spread trees.

The paper's whole value proposition is epidemic dissemination — a write
on one node becomes visible everywhere within a bounded number of
anti-entropy rounds — and this module makes that process *observable*:
how did key K version V reach node X, along which hops, and how long
after the owner's write?

Recording side (runtime/engine.py + runtime/cluster.py, attached via
``Cluster.trace_provenance`` / ``ChaosHarness(prov_trace=...)`` — OFF
by default, byte-identical hot paths when detached):

- ``prov_write``  — origin: the owner wrote (key, version) at ``t_mono``.
- ``prov_apply``  — receiver side: ``node`` applied owner's (key,
  version); ``from_peer`` names the peer the delta came from when the
  receiver knows it (initiator-side applies — it dialed the peer; Leave
  announcements — the message names the leaver) and is null on
  responder-side applies (a Syn carries no sender identity and the wire
  stays unchanged).
- ``prov_send``   — sender side for exactly that blind spot: when an
  initiator packs the Ack delta it knows the responder it is talking
  to, so it records (to_peer, key, version, t_mono) and the collector
  joins the responder's null-``from_peer`` apply to the closest
  preceding matching send.

Clock contract: ``t_mono`` is CLOCK_MONOTONIC, comparable across the
processes of one machine — the same assumption serve_bench's
cross-process watch latencies already rely on (loopback fleets). Wall
``ts`` rides every record for log correlation only; no clock protocol
is introduced.

``join_propagation`` groups the events per (owner, key, version) and
builds one :class:`SpreadTree` each: per-node first-visibility latency
(write→apply), the hop graph (parent pointers resolved from
``from_peer`` or the send join), and hop depths (graph distance from
the owner). ``benchmarks/propagation_bench.py`` gates on it;
``ChaosHarness.propagation_report()`` is the fleet-level entry point.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from .registry import percentile_of_sorted

# A send strictly newer than the apply it would explain cannot be its
# cause; a send this much older than the apply (seconds) is a previous
# round's traffic. The window only disambiguates CONCURRENT senders of
# the same kv — first-apply-wins means at most one send actually landed.
_SEND_JOIN_HORIZON_S = 30.0


@dataclass
class NodeVisibility:
    """One node's first sighting of a (owner, key, version)."""

    node: str
    t_mono: float
    from_peer: str | None  # named by the receiver, or resolved via send join
    join: str  # "origin" | "direct" | "send" | "unjoined"
    latency_s: float | None = None  # write -> first visibility
    hop: int | None = None  # graph distance from the owner


@dataclass
class SpreadTree:
    """The epidemic spread of one (owner, key, version)."""

    owner: str
    key: str
    version: int
    origin_t: float | None  # the owner's prov_write t_mono (None if unseen)
    nodes: dict[str, NodeVisibility] = field(default_factory=dict)

    # -- derived --------------------------------------------------------------

    def applies(self) -> list[NodeVisibility]:
        """Non-owner visibilities (the fleet's applies), time order."""
        return sorted(
            (v for v in self.nodes.values() if v.node != self.owner),
            key=lambda v: v.t_mono,
        )

    def joined_fraction(self, fleet_size: int) -> float:
        """Fraction of the non-owner fleet whose apply the collector
        joined into this tree — the prov-smoke gate reads this."""
        expected = max(fleet_size - 1, 1)
        return len(self.applies()) / expected

    def latencies(self) -> list[float]:
        return sorted(
            v.latency_s for v in self.applies() if v.latency_s is not None
        )

    def visibility_percentile(self, q: float) -> float:
        """Write→visible latency at quantile ``q`` over the fleet's
        applies (nearest-rank — the repo's shared convention)."""
        return percentile_of_sorted(self.latencies(), q)

    def hop_histogram(self) -> dict[int, int]:
        """hop depth -> node count (owner at 0; unresolved hops are
        excluded — ``unjoined_hops`` counts them)."""
        hist: dict[int, int] = {}
        for v in self.nodes.values():
            if v.hop is not None:
                hist[v.hop] = hist.get(v.hop, 0) + 1
        return dict(sorted(hist.items()))

    def hops_percentile(self, q: float) -> float:
        hops = sorted(
            v.hop for v in self.applies() if v.hop is not None
        )
        return percentile_of_sorted(hops, q)

    @property
    def unjoined_hops(self) -> int:
        """Applies whose hop parent could not be resolved (no
        ``from_peer`` and no matching send — e.g. a torn trace)."""
        return sum(1 for v in self.applies() if v.hop is None)

    def join_kinds(self) -> dict[str, int]:
        """Apply count per join kind — ``direct`` (the receiver named
        its peer: it dialed, a Leave named its sender, or the wire's
        trace context carried it), ``send`` (the legacy
        closest-preceding-send heuristic), ``unjoined``."""
        counts: dict[str, int] = {}
        for v in self.applies():
            counts[v.join] = counts.get(v.join, 0) + 1
        return dict(sorted(counts.items()))

    def exact_join_fraction(self) -> float | None:
        """Fraction of this tree's applies joined EXACTLY (kind
        ``direct``) rather than by heuristic or not at all — 1.0 is the
        fleet_bench gate with ``Config.trace_context`` on. None when
        there are no applies to judge."""
        applies = self.applies()
        if not applies:
            return None
        exact = sum(1 for v in applies if v.join == "direct")
        return exact / len(applies)

    def summary(self, fleet_size: int | None = None) -> dict:
        out = {
            "owner": self.owner,
            "key": self.key,
            "version": self.version,
            "applies": len(self.applies()),
            "unjoined_hops": self.unjoined_hops,
            "hop_histogram": {
                str(k): v for k, v in self.hop_histogram().items()
            },
            "join_kinds": self.join_kinds(),
        }
        exact = self.exact_join_fraction()
        if exact is not None:
            out["exact_join_frac"] = round(exact, 4)
        lats = self.latencies()
        if lats:
            out["visibility_p50_s"] = round(
                percentile_of_sorted(lats, 0.50), 6
            )
            out["visibility_p99_s"] = round(
                percentile_of_sorted(lats, 0.99), 6
            )
            out["visibility_max_s"] = round(lats[-1], 6)
        hops = sorted(
            v.hop for v in self.applies() if v.hop is not None
        )
        if hops:
            out["hops_p50"] = percentile_of_sorted(hops, 0.50)
            out["hops_p99"] = percentile_of_sorted(hops, 0.99)
            out["hops_max"] = hops[-1]
        if fleet_size is not None:
            out["joined_fraction"] = round(
                self.joined_fraction(fleet_size), 4
            )
        return out


@dataclass
class PropagationReport:
    """All spread trees joined from one trace (or trace set)."""

    trees: dict[tuple[str, str, int], SpreadTree]
    records_seen: int = 0

    def tree(
        self, *, owner: str, key: str, version: int | None = None
    ) -> SpreadTree | None:
        """The tree for (owner, key) — the highest version unless one is
        named (a marked write is usually the key's latest)."""
        matches = [
            t
            for (o, k, _v), t in self.trees.items()
            if o == owner and k == key
        ]
        if version is not None:
            matches = [t for t in matches if t.version == version]
        if not matches:
            return None
        return max(matches, key=lambda t: t.version)


def _records_of(traces) -> list[dict]:
    """Accept a record list, one path, or an iterable of paths; paths
    are read tolerantly (a torn tail must not lose the whole join)."""
    from .trace import read_trace

    if isinstance(traces, (str, Path)):
        return read_trace(traces, skip_invalid=True)
    traces = list(traces)
    if traces and isinstance(traces[0], (str, Path)):
        records: list[dict] = []
        for p in traces:
            records.extend(read_trace(p, skip_invalid=True))
        return records
    return traces


def join_propagation(traces, *, key: str | None = None) -> PropagationReport:
    """Join provenance events into per-(owner, key, version) spread
    trees (module docstring). ``traces`` is a list of parsed records, a
    trace path, or several paths (fleets usually share ONE lock-
    serialized writer, so one path is the common case). ``key`` filters
    the join to one key's trees (a marked-write study skips the
    bootstrap traffic entirely)."""
    records = _records_of(traces)
    writes: dict[tuple[str, str, int], dict] = {}
    applies: list[dict] = []
    sends: list[dict] = []
    for rec in records:
        event = rec.get("event")
        if event not in ("prov_write", "prov_apply", "prov_send"):
            continue
        if key is not None and rec.get("key") != key:
            continue
        if event == "prov_write":
            ident = (rec["node"], rec["key"], int(rec["version"]))
            prev = writes.get(ident)
            # First write wins: re-journaled or duplicate records must
            # not move the origin timestamp later.
            if prev is None or rec["t_mono"] < prev["t_mono"]:
                writes[ident] = rec
        elif event == "prov_apply":
            applies.append(rec)
        else:
            sends.append(rec)

    trees: dict[tuple[str, str, int], SpreadTree] = {}

    def tree_for(owner: str, k: str, version: int) -> SpreadTree:
        ident = (owner, k, version)
        t = trees.get(ident)
        if t is None:
            w = writes.get(ident)
            t = trees[ident] = SpreadTree(
                owner=owner,
                key=k,
                version=version,
                origin_t=None if w is None else float(w["t_mono"]),
            )
            if w is not None:
                t.nodes[owner] = NodeVisibility(
                    node=owner,
                    t_mono=float(w["t_mono"]),
                    from_peer=None,
                    join="origin",
                    latency_s=0.0,
                    hop=0,
                )
        return t

    # Writes with no applies still deserve a (trivial) tree.
    for owner, k, version in writes:
        tree_for(owner, k, version)

    # Sends indexed by (owner, key, version, to_peer) for the
    # responder-side join; each list kept in time order.
    send_index: dict[tuple[str, str, int, str], list[dict]] = {}
    for rec in sends:
        send_index.setdefault(
            (rec["owner"], rec["key"], int(rec["version"]), rec["to_peer"]),
            [],
        ).append(rec)
    for lst in send_index.values():
        lst.sort(key=lambda r: r["t_mono"])

    for rec in sorted(applies, key=lambda r: r["t_mono"]):
        owner = rec["owner"]
        k = rec["key"]
        version = int(rec["version"])
        node = rec["node"]
        t = tree_for(owner, k, version)
        if node in t.nodes:
            continue  # first visibility wins (idempotent re-applies)
        t_mono = float(rec["t_mono"])
        from_peer = rec.get("from_peer")
        join = "direct" if from_peer else "unjoined"
        if not from_peer:
            # Responder-side apply: the closest preceding matching send
            # names the initiator that carried the kv here.
            candidates = send_index.get((owner, k, version, node), ())
            best = None
            for s in candidates:
                if s["t_mono"] > t_mono:
                    break
                if t_mono - s["t_mono"] <= _SEND_JOIN_HORIZON_S:
                    best = s
            if best is not None:
                from_peer = best["node"]
                join = "send"
        latency = None
        if t.origin_t is not None:
            latency = max(t_mono - t.origin_t, 0.0)
        t.nodes[node] = NodeVisibility(
            node=node,
            t_mono=t_mono,
            from_peer=from_peer,
            join=join,
            latency_s=latency,
        )

    # Hop depths: graph distance from the owner along parent pointers.
    # Parents may resolve in any order (a child's apply can be recorded
    # before its parent's when the parent was the origin's responder),
    # so iterate to a fixed point; unresolved chains stay None.
    for t in trees.values():
        changed = True
        guard = len(t.nodes) + 1  # cycle guard: depth can't exceed N
        while changed and guard:
            changed = False
            guard -= 1
            for v in t.nodes.values():
                if v.hop is not None:
                    continue
                if v.from_peer == t.owner or (
                    v.from_peer is None and v.join == "origin"
                ):
                    v.hop = 0 if v.join == "origin" else 1
                    changed = True
                elif v.from_peer is not None:
                    parent = t.nodes.get(v.from_peer)
                    if parent is not None and parent.hop is not None:
                        v.hop = parent.hop + 1
                        changed = True
    return PropagationReport(trees=trees, records_seen=len(records))
