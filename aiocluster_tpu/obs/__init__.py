"""Unified telemetry: metrics registry, traces, exposition, profiling.

The reference has no tracing, metrics, or profiling at all (SURVEY.md §5);
this package is the measurement substrate both backends report through:

- ``registry``: dependency-free counters/gauges/histograms with labels,
  safe to update from asyncio callbacks and worker threads alike.
- ``expo``: Prometheus text-format rendering of a registry, plus an
  optional asyncio HTTP ``/metrics`` endpoint (stdlib only).
- ``trace``: a JSONL trace writer for per-round/per-event records, with a
  reader for round-trips and offline analysis.
- ``prov``: the propagation-provenance collector — joins per-node
  ``prov_write``/``prov_apply``/``prov_send`` trace events into
  per-(key, version) epidemic spread trees (hop graphs, write→visible
  latency percentiles).
- ``flightrec``: the always-on bounded ring of recent annotated events
  every Cluster carries for post-mortems (``/debug/flightrec``).
- ``profiling``: the XLA device trace + wall-clock section timer that
  used to live in ``utils/profiling.py``.

Both the runtime layer (runtime/cluster.py and friends) and the sim layer
(sim/simulator.py, sim/hostsim.py) accept a ``MetricsRegistry`` and emit
through it; ``python -m aiocluster_tpu`` wires ``--metrics-port`` and
``--trace-file`` to these pieces, and bench.py embeds a registry snapshot
in every BENCH record. docs/observability.md catalogues the metric names.
"""

from .expo import MetricsHTTPServer, render_prometheus
from .fleet import (
    TELEMETRY_KEY,
    TELEMETRY_PREFIX,
    FleetEntry,
    assemble_fleet_view,
    build_fleet_entry,
    decode_health_digest,
    encode_health_digest,
)
from .flightrec import FlightRecorder
from .profiling import SectionTimer, device_trace
from .prov import PropagationReport, SpreadTree, join_propagation
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    percentile_of_sorted,
)
from .sim import SimMetrics, SweepMetrics, marked_write_state, wavefront_series
from .trace import TRACE_SCHEMA, TraceScan, TraceWriter, read_trace, scan_trace

__all__ = (
    "Counter",
    "FleetEntry",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "PropagationReport",
    "SectionTimer",
    "SimMetrics",
    "SpreadTree",
    "SweepMetrics",
    "TELEMETRY_KEY",
    "TELEMETRY_PREFIX",
    "TRACE_SCHEMA",
    "TraceScan",
    "TraceWriter",
    "assemble_fleet_view",
    "build_fleet_entry",
    "decode_health_digest",
    "default_registry",
    "device_trace",
    "encode_health_digest",
    "join_propagation",
    "marked_write_state",
    "percentile_of_sorted",
    "read_trace",
    "render_prometheus",
    "scan_trace",
    "wavefront_series",
)
