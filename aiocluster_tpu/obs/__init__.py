"""Unified telemetry: metrics registry, traces, exposition, profiling.

The reference has no tracing, metrics, or profiling at all (SURVEY.md §5);
this package is the measurement substrate both backends report through:

- ``registry``: dependency-free counters/gauges/histograms with labels,
  safe to update from asyncio callbacks and worker threads alike.
- ``expo``: Prometheus text-format rendering of a registry, plus an
  optional asyncio HTTP ``/metrics`` endpoint (stdlib only).
- ``trace``: a JSONL trace writer for per-round/per-event records, with a
  reader for round-trips and offline analysis.
- ``profiling``: the XLA device trace + wall-clock section timer that
  used to live in ``utils/profiling.py``.

Both the runtime layer (runtime/cluster.py and friends) and the sim layer
(sim/simulator.py, sim/hostsim.py) accept a ``MetricsRegistry`` and emit
through it; ``python -m aiocluster_tpu`` wires ``--metrics-port`` and
``--trace-file`` to these pieces, and bench.py embeds a registry snapshot
in every BENCH record. docs/observability.md catalogues the metric names.
"""

from .expo import MetricsHTTPServer, render_prometheus
from .profiling import SectionTimer, device_trace
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from .sim import SimMetrics, SweepMetrics
from .trace import TRACE_SCHEMA, TraceScan, TraceWriter, read_trace, scan_trace

__all__ = (
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "SectionTimer",
    "SimMetrics",
    "SweepMetrics",
    "TRACE_SCHEMA",
    "TraceScan",
    "TraceWriter",
    "default_registry",
    "device_trace",
    "read_trace",
    "render_prometheus",
    "scan_trace",
)
