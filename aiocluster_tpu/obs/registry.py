"""Dependency-free metrics registry: counters, gauges, histograms.

Model (a deliberately small slice of the Prometheus client data model):
a registry owns named metric families; a family plus one concrete label
set is a *child* holding the actual value. Families are created lazily
and idempotently — ``registry.counter("x", ...)`` returns the existing
family on the second call — so instrumented modules never coordinate
creation order.

Thread/task safety: one registry-wide ``threading.Lock`` guards child
creation and every value update. Updates are a few dict/float ops, so the
lock is uncontended in practice (asyncio callbacks all run on one thread;
the lock exists for bench/sim worker threads and the /metrics server
thread reading a snapshot mid-run).
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from collections.abc import Iterable, Sequence

LabelValues = tuple[str, ...]

# Histogram default: latency-shaped (seconds), two decades around a
# gossip interval, mirroring Prometheus' client defaults closely enough
# that dashboards carry over.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def percentile_of_sorted(sorted_values, q: float) -> float:
    """Nearest-rank percentile of an already-sorted sequence of raw
    samples: ``idx = round(q * (n - 1))`` — THE percentile convention
    every measured figure in this repo shares (bench watch latencies,
    the sim staleness picks, the propagation report). NaN on empty
    input, so a missing series reads as missing rather than 0."""
    n = len(sorted_values)
    if not n:
        return float("nan")
    idx = min(n - 1, int(q * (n - 1) + 0.5))
    return sorted_values[idx]


def _bucket_quantile(
    buckets: list[tuple[float, int]], count: int, q: float
) -> float | None:
    """Bucket-interpolated quantile over ONE atomic ``stats()`` read —
    the shared math behind ``_HistogramValue.quantile`` and
    ``snapshot()``'s p50/p99 (both quantiles of a snapshot entry come
    from the same read as its count/sum, so a concurrent ``observe()``
    can never make them disagree). Prometheus ``histogram_quantile``
    conventions: a positive first bound interpolates from 0, a
    non-positive first bound is returned as-is (0 is not a valid lower
    anchor below it), and a rank landing in the +Inf bucket clamps to
    the highest finite bound."""
    if count == 0:
        return None
    rank = q * count
    prev_bound = 0.0
    prev_cum = 0
    for i, (bound, cum) in enumerate(buckets):
        if rank <= cum:
            if bound == float("inf"):
                return prev_bound  # open-ended bucket: clamp
            if i == 0 and bound <= 0:
                return bound
            if cum == prev_cum:  # defensive: rank == cum == prev_cum
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = bound, cum
    return prev_bound  # unreachable: +Inf always covers the rank


def _validate_name(name: str) -> str:
    if not name or not all(c.isalnum() or c in "_:" for c in name):
        raise ValueError(f"invalid metric name: {name!r}")
    if name[0].isdigit():
        raise ValueError(f"metric name cannot start with a digit: {name!r}")
    return name


class _Family:
    """One named metric family: children keyed by label values."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help_text: str,
        label_names: Sequence[str],
        lock: threading.Lock,
    ) -> None:
        self.name = _validate_name(name)
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = lock
        self._children: dict[LabelValues, object] = {}

    def labels(self, *values: str):
        """The child for one concrete label set (created on first use)."""
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label "
                f"value(s) {self.label_names}, got {len(values)}"
            )
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def remove(self, *values: str) -> None:
        """Drop one concrete label series. Per-peer families (e.g.
        ``aiocluster_breaker_state{peer}``) call this when the peer is
        garbage-collected from membership — without eviction the series
        set grows monotonically with cumulative address churn."""
        key = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(key, None)

    def _make_child(self) -> object:
        raise NotImplementedError

    def samples(self) -> list[tuple[LabelValues, object]]:
        with self._lock:
            return list(self._children.items())


class _CounterValue:
    __slots__ = ("_value", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self._value = 0.0
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Counter(_Family):
    """Monotonically increasing count (events, bytes, packets)."""

    kind = "counter"

    def _make_child(self) -> _CounterValue:
        return _CounterValue(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        """Label-less convenience: ``family.inc()`` on a 0-label family."""
        self.labels().inc(amount)


class _GaugeValue:
    __slots__ = ("_value", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self._value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Family):
    """Point-in-time value (queue depth, alive count, fraction)."""

    kind = "gauge"

    def _make_child(self) -> _GaugeValue:
        return _GaugeValue(self._lock)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)


class _HistogramValue:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds: tuple[float, ...], lock: threading.Lock) -> None:
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +inf tail bucket
        self._sum = 0.0
        self._count = 0
        self._lock = lock

    def observe(self, value: float) -> None:
        idx = bisect_right(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    def stats(self) -> tuple[list[tuple[float, int]], float, int]:
        """One ATOMIC read of (cumulative buckets, sum, count): a scraper
        thread must never see a +Inf bucket that disagrees with _count
        because an observe() landed between two reads."""
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
            total_count = self._count
        out: list[tuple[float, int]] = []
        running = 0
        for bound, c in zip(self._bounds, counts):
            running += c
            out.append((bound, running))
        out.append((float("inf"), running + counts[-1]))
        return out, total_sum, total_count

    def buckets(self) -> list[tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs, +inf last — the
        Prometheus exposition shape."""
        return self.stats()[0]

    def quantile(self, q: float) -> float | None:
        """Bucket-interpolated quantile in [0, 1] — the
        ``histogram_quantile`` convention (see ``_bucket_quantile``),
        computed server-side so round-latency/RTT/phi percentiles are a
        registry read, not a bench-only recomputation over raw samples.
        None when the histogram is empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile q must be in [0, 1]")
        buckets, _, count = self.stats()
        return _bucket_quantile(buckets, count, q)


class Histogram(_Family):
    """Distribution with cumulative buckets (latencies, phi values)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str,
        label_names: Sequence[str],
        lock: threading.Lock,
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help_text, label_names, lock)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds

    def _make_child(self) -> _HistogramValue:
        return _HistogramValue(self.bounds, self._lock)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def quantile(self, q: float) -> float | None:
        """Label-less convenience: bucket-interpolated quantile of the
        0-label child (see ``_HistogramValue.quantile``)."""
        return self.labels().quantile(q)


class MetricsRegistry:
    """Owns metric families; the unit of exposition and snapshotting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, cls, name, help_text, label_names, **kwargs):
        # Check-validate-create under ONE lock hold: a race on first
        # registration must not let a conflicting definition slip past
        # the kind/label/bucket validation below.
        with self._lock:
            family = self._families.get(name)
            if family is not None:
                if not isinstance(family, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {family.kind}"
                    )
                if family.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{family.label_names}"
                    )
                if "buckets" in kwargs:
                    bounds = tuple(sorted(float(b) for b in kwargs["buckets"]))
                    if bounds != family.bounds:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {family.bounds}"
                        )
                return family
            created = cls(
                name, help_text, tuple(label_names), self._lock, **kwargs
            )
            self._families[name] = created
            return created

    def counter(
        self, name: str, help_text: str = "", labels: Sequence[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(
        self, name: str, help_text: str = "", labels: Sequence[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labels: Sequence[str] = (),
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, labels, buckets=buckets
        )

    def families(self) -> list[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def snapshot(self) -> dict[str, object]:
        """Flat JSON-friendly view: one entry per (family, label set).
        Histograms compress to {count, sum, mean, p50, p99}
        (bucket-interpolated quantiles — so latency/RTT/phi percentiles
        ride every snapshot, bench ``metrics_snapshot`` embeds
        included, instead of being recomputed per consumer); this is
        the shape bench.py embeds in BENCH records."""
        out: dict[str, object] = {}
        for family in self.families():
            for values, child in family.samples():
                key = family.name
                if values:
                    key += "{" + ",".join(
                        f"{n}={v}"
                        for n, v in zip(family.label_names, values)
                    ) + "}"
                if isinstance(child, _HistogramValue):
                    # ONE atomic stats() read feeds count, sum AND both
                    # quantiles — an observe() landing mid-snapshot can
                    # never make the entry disagree with itself.
                    buckets, total_sum, count = child.stats()
                    p50 = _bucket_quantile(buckets, count, 0.50)
                    p99 = _bucket_quantile(buckets, count, 0.99)
                    out[key] = {
                        "count": count,
                        "sum": round(total_sum, 9),
                        "mean": round(total_sum / count, 9) if count else None,
                        "p50": None if p50 is None else round(p50, 9),
                        "p99": None if p99 is None else round(p99, 9),
                    }
                else:
                    out[key] = child.value  # type: ignore[attr-defined]
        return out


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry instrumented code falls back to when the
    caller doesn't inject one — the ``/metrics`` endpoint serves this
    unless told otherwise."""
    return _default
