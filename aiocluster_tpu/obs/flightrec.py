"""Flight recorder: a bounded ring of recent annotated events per node.

Metrics aggregate away the *sequence* of what happened; traces are
opt-in and cost a disk write per event. The flight recorder is the
third shape the post-mortem needs: an ALWAYS-ON, bounded, in-memory
ring of the last few hundred notable events — handshake outcomes, FD
flips, breaker transitions, guard rejections, applies, lifecycle steps
— dumped on demand (``Cluster.flight_record()``,
``GET /debug/flightrec`` on the serve tier) when an operator asks "what
did this node just live through?".

Cost discipline (why always-on is safe): ``note()`` is two clock reads,
a small tuple, and a ``deque.append`` with ``maxlen`` eviction — no
formatting, no I/O, no allocation proportional to anything; events are
rendered to dicts only at ``dump()``. The ring is bounded by
construction, so a chatty subsystem can age out history but never grow
memory.

Timestamps carry BOTH clocks: ``t_mono`` (monotonic — the clock the
provenance tracer and serve_bench subtract across processes on loopback
fleets) and ``ts`` (wall — what the operator correlates with their
logs). Both come from the ``utils.clock`` seam, so a recorder living in
a virtual-time run (docs/virtual-time.md) stamps virtual instants — the
byte-identical-replay currency of tests/test_vtime.py.
"""

from __future__ import annotations

import threading
from collections import deque

from ..utils.clock import Clock, resolve_clock

# Default ring capacity. A gossip round produces O(fanout) handshake
# events, so 512 covers minutes of quiet operation and the last dozens
# of seconds of a storm — the window a post-mortem actually reads.
DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Bounded ring buffer of (t_mono, ts, kind, fields) events."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        *,
        clock: Clock | None = None,
    ) -> None:
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = capacity
        self._clock = resolve_clock(clock)
        self._ring: deque[tuple[float, float, str, dict]] = deque(
            maxlen=capacity
        )
        # deque.append is atomic, but dump() iterates — the lock keeps a
        # /metrics-thread dump from racing an asyncio-callback append.
        self._lock = threading.Lock()
        self.events_noted = 0  # total ever, not just retained

    def note(self, kind: str, **fields: object) -> None:
        """Record one event. Hot-path safe: no formatting, no I/O."""
        entry = (self._clock.monotonic(), self._clock.wall(), kind, fields)
        with self._lock:
            self._ring.append(entry)
            self.events_noted += 1

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self) -> list[dict]:
        """The retained events, oldest first, as JSON-ready dicts (the
        one place entries are formatted)."""
        with self._lock:
            entries = list(self._ring)
            total = self.events_noted
        out = []
        dropped = total - len(entries)
        for t_mono, ts, kind, fields in entries:
            out.append(
                {
                    "t_mono": round(t_mono, 6),
                    "ts": round(ts, 6),
                    "kind": kind,
                    **fields,
                }
            )
        if out:
            # Honesty marker on the first retained record: how many
            # older events the ring has already aged out.
            out[0] = {"evicted_before": dropped, **out[0]}
        return out
