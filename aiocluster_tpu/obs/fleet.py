"""Fleet telemetry plane: gossip-borne node health and any-member views.

The reference's production use-case (quickwit's chitchat) is exactly
this pattern: nodes gossip their own liveness/health metadata and any
member answers for the whole fleet. This module makes it a first-class,
guarded, staleness-annotated surface (docs/observability.md "Fleet
telemetry"; docs/migration.md difference #17):

- **Self-telemetry keys.** When ``Config.telemetry_interval`` is set,
  each node periodically folds a compact versioned digest of its own
  health into its OWN keyspace under :data:`TELEMETRY_KEY` — one plain
  owner write per interval, riding the existing owner-write invariant,
  byzantine guards, segments fastpath and MTU budget. One write per
  interval means at most one content-epoch bump per interval, so the
  serve tier's SnapshotCache heartbeat dedup and shared payloads stay
  effective.

- **Fleet views.** ``Cluster.fleet_view()`` (and ``GET /fleet``, and
  ``python -m aiocluster_tpu fleet``) assembles the replicated
  telemetry into a per-node table. Each entry carries *staleness*: the
  lag between the owner's advertised heartbeat (stamped into the digest
  at publish time) and the local heartbeat watermark for that owner —
  the concrete per-member epoch vector ROADMAP item 2a asks for,
  converted to approximate seconds via the owner's advertised gossip
  interval (an upper bound: inbound handshakes also advance
  heartbeats).

- **Suspect marking.** A digest advertising a heartbeat ABOVE the
  local failure detector's known watermark cannot have come from the
  owner's normal publish cadence (the watermark replicates with or
  ahead of the key); the entry is marked ``suspect`` rather than
  trusted. Forged telemetry *for* a victim's keyspace never gets this
  far — the owner-violation guard rejects and counts it
  (core/guards.py, tests/test_byzantine.py).

The wire stays byte-identical when telemetry is off: no key is ever
written, nothing is appended to any frame.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from .registry import percentile_of_sorted

# Reserved key prefix for gossip-borne self-telemetry. Code in
# runtime/serve/obs must reference this constant instead of repeating
# the literal (analyzer rule ACT043, docs/static-analysis.md) — the
# prefix is the contract boundary between application keys and the
# telemetry plane.
TELEMETRY_PREFIX = "__fleet:"

# The one self-telemetry key each node owns (schema below).
TELEMETRY_KEY = TELEMETRY_PREFIX + "health"

# Digest schema version, stamped into every payload as ``v``. Decoders
# accept any payload whose version they can read; unknown future fields
# are carried through untouched.
TELEMETRY_SCHEMA_VERSION = 1


def encode_health_digest(fields: dict) -> str:
    """Compact JSON encoding of one node's health digest. ``fields``
    uses the short keys documented in docs/observability.md ("Fleet
    telemetry" key schema); the schema version is stamped here so every
    publish site agrees."""
    payload = dict(fields)
    payload["v"] = TELEMETRY_SCHEMA_VERSION
    return json.dumps(payload, sort_keys=True, separators=(",", ":"))


def decode_health_digest(raw: str | None) -> dict | None:
    """Tolerant decode of a replicated telemetry value: ``None`` (and
    never an exception) for a missing, unparsable, or non-object
    payload — a malformed digest from one node must not take down
    another node's fleet view."""
    if not raw:
        return None
    try:
        payload = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(payload, dict) or "v" not in payload:
        return None
    return payload


def round_latency_percentiles(durations) -> tuple[float, float] | None:
    """(p50, p99) over recent gossip-round wall durations (seconds),
    nearest-rank — the repo's shared percentile convention. None when
    there are no samples yet."""
    samples = sorted(float(d) for d in durations)
    if not samples:
        return None
    return (
        percentile_of_sorted(samples, 0.50),
        percentile_of_sorted(samples, 0.99),
    )


@dataclass(slots=True)
class FleetEntry:
    """One node's row in a fleet view."""

    node: str
    live: bool
    heartbeat_local: int  # this member's replicated watermark for the owner
    digest: dict | None = None  # decoded telemetry payload (None = no key yet)
    heartbeat_advertised: int | None = None  # ``hb`` stamped at publish time
    staleness_beats: int | None = None  # local watermark - advertised
    staleness_s: float | None = None  # beats x advertised interval (approx)
    suspect: bool = False  # advertised heartbeat ABOVE the local watermark

    def as_dict(self) -> dict:
        return {
            "node": self.node,
            "live": self.live,
            "heartbeat_local": self.heartbeat_local,
            "heartbeat_advertised": self.heartbeat_advertised,
            "staleness_beats": self.staleness_beats,
            "staleness_s": self.staleness_s,
            "suspect": self.suspect,
            "digest": self.digest,
        }


def build_fleet_entry(
    name: str, *, live: bool, heartbeat: int, raw: str | None
) -> FleetEntry:
    """One node's entry from its locally-replicated state: decode the
    telemetry value and annotate staleness/suspicion against the local
    heartbeat watermark (module docstring has the semantics)."""
    entry = FleetEntry(node=name, live=live, heartbeat_local=int(heartbeat))
    digest = decode_health_digest(raw)
    if digest is None:
        return entry
    entry.digest = digest
    adv = digest.get("hb")
    if not isinstance(adv, int):
        return entry
    entry.heartbeat_advertised = adv
    if adv > entry.heartbeat_local:
        # The digest claims a heartbeat the local FD has never credited:
        # it cannot be the owner's honest publish (the watermark
        # replicates with or ahead of the key). Flag, don't trust.
        entry.suspect = True
        return entry
    entry.staleness_beats = entry.heartbeat_local - adv
    interval = digest.get("int")
    if isinstance(interval, (int, float)) and interval > 0:
        entry.staleness_s = round(entry.staleness_beats * float(interval), 6)
    return entry


def assemble_fleet_view(
    entries: list[FleetEntry],
    *,
    self_name: str,
    epoch: int,
    stale_s: float | None = None,
) -> dict:
    """The fleet table ``Cluster.fleet_view()`` / ``GET /fleet`` serve:
    per-node entries plus coverage and staleness aggregates. With
    ``stale_s`` set, entries whose staleness exceeds it — or is unknown
    (no telemetry, suspect, or no advertised interval) — are filtered
    out, except the assembling member itself (its own entry is local by
    definition)."""
    covered = sum(1 for e in entries if e.heartbeat_advertised is not None)
    suspect = sum(1 for e in entries if e.suspect)
    stale_values = sorted(
        e.staleness_s for e in entries if e.staleness_s is not None
    )
    shown = entries
    if stale_s is not None:
        shown = [
            e
            for e in entries
            if e.node == self_name
            or (e.staleness_s is not None and e.staleness_s <= stale_s)
        ]
    view = {
        "self": self_name,
        "epoch": epoch,
        "known": len(entries),
        "covered": covered,
        "coverage_frac": round(covered / len(entries), 4) if entries else 0.0,
        "suspect": suspect,
        "stale_s": stale_s,
        "nodes": {e.node: e.as_dict() for e in shown},
    }
    if stale_values:
        view["staleness_p50_s"] = percentile_of_sorted(stale_values, 0.50)
        view["staleness_p99_s"] = percentile_of_sorted(stale_values, 0.99)
        view["staleness_max_s"] = stale_values[-1]
    return view
