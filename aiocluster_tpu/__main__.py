"""Command-line entry points: ``python -m aiocluster_tpu {node,sim,...}``.

The reference is library-only (no CLI); these subcommands make both
backends usable without writing code:

- ``node`` boots one asyncio cluster node (reference examples/simple.py
  shape) and prints a snapshot line per gossip interval until Ctrl-C.
- ``sim`` runs a tensor-sim convergence study and prints one JSON line
  of results (metrics + rounds to convergence).
- ``twin`` replays a recorded trace into the digital twin (docs/twin.md).
- ``fleet`` asks any member's serve tier for its fleet view (GET /fleet,
  obs/fleet.py) and renders the per-node health table.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _parse_addr(text: str) -> tuple[str, int]:
    host, _, port = text.rpartition(":")
    if not host or not port.isdigit():
        raise argparse.ArgumentTypeError(f"expected HOST:PORT, got {text!r}")
    return host, int(port)


def _parse_kv(text: str) -> tuple[str, str]:
    key, sep, value = text.partition("=")
    if not sep or not key:
        raise argparse.ArgumentTypeError(f"expected KEY=VALUE, got {text!r}")
    return key, value


async def _run_node(args: argparse.Namespace) -> int:
    from . import Cluster, Config, NodeId
    from .obs import MetricsHTTPServer, TraceWriter, default_registry

    cfg = Config(
        node_id=NodeId(name=args.name, gossip_advertise_addr=args.listen),
        cluster_id=args.cluster_id,
        seed_nodes=args.seed,
        gossip_interval=args.interval,
    )
    trace = TraceWriter(args.trace_file) if args.trace_file else None
    metrics_server = None
    try:
        if args.metrics_port is not None:
            metrics_server = MetricsHTTPServer(
                default_registry(), port=args.metrics_port
            )
            port = await metrics_server.start()
            print(f"[{args.name}] /metrics on 127.0.0.1:{port}",
                  file=sys.stderr, flush=True)
        async with Cluster(
            cfg, initial_key_values=dict(args.set or []), trace=trace
        ) as cluster:
            print(f"[{args.name}] listening on {args.listen[0]}:{args.listen[1]}",
                  file=sys.stderr, flush=True)
            # No CancelledError handler here (ACT013 audit): Ctrl-C
            # cancellation propagates — the async-with closes the
            # cluster, the finally below closes telemetry, and main()
            # turns the resulting KeyboardInterrupt into exit 0.
            while True:
                await asyncio.sleep(args.interval)
                snap = cluster.snapshot()
                live = sorted(n.name for n in snap.live_nodes)
                print(json.dumps({
                    "node": args.name,
                    "live": live,
                    "nodes_known": len(snap.node_states),
                }), flush=True)
    finally:
        if metrics_server is not None:
            await metrics_server.stop()
        if trace is not None:
            trace.close()
    return 0


def _sim_config(args: argparse.Namespace):
    """Build the SimConfig from CLI flags. ValueErrors raised here are
    user errors (bad --mtu/--nodes/--grace combinations) and surface as
    clean parser errors; anything raised later in the run is a real bug
    and keeps its traceback."""
    from .core import DEFAULT_MAX_PAYLOAD_SIZE
    from .sim import SimConfig, budget_from_mtu

    if args.lean and args.keys >= 2**15:
        # The lean profile's int16 watermarks cap initial versions; catch
        # it here so it surfaces as a clean parser error, not a traceback
        # from init_state.
        raise ValueError(
            f"--lean stores int16 watermarks: --keys {args.keys} >= 32768 "
            "overflows (drop --lean or lower --keys)"
        )
    # --host-native without --lean runs the FULL profile natively, which
    # requires the scale dtypes (sim.memory.full_config's int16 ticks +
    # bf16 stored means — hostsim.supported); they are exact on the
    # CLI's horizon and also what any at-scale device run should use.
    narrow = args.lean or getattr(args, "host_native", False)
    return SimConfig(
        n_nodes=args.nodes,
        keys_per_node=args.keys,
        fanout=args.fanout,
        budget=budget_from_mtu(
            args.mtu if args.mtu is not None else DEFAULT_MAX_PAYLOAD_SIZE
        ),
        death_rate=args.churn,
        revival_rate=4 * args.churn,
        track_failure_detector=not args.lean,
        track_heartbeats=not args.lean,
        # The same profile sim.memory.lean_config prescribes: int16
        # watermarks are what buy the memory headroom at max scale.
        version_dtype="int16" if narrow else "int32",
        heartbeat_dtype="int16" if narrow else "int32",
        fd_dtype="bfloat16" if narrow else "float32",
        dead_grace_ticks=args.grace if args.churn and not args.lean else None,
    )


def _make_telemetry(args: argparse.Namespace):
    """(registry, trace, server, obs_kwargs) from the CLI flags. Telemetry
    is opt-in: without --metrics-port/--trace-file the sim constructors
    get no registry and the hot loop carries zero obs dispatches."""
    from .obs import MetricsHTTPServer, TraceWriter, default_registry

    trace = TraceWriter(args.trace_file) if args.trace_file else None
    server = None
    registry = None
    if args.metrics_port is not None:
        registry = default_registry()
        server = MetricsHTTPServer(registry, port=args.metrics_port)
        try:
            port = server.start_in_thread()
        except BaseException:
            if trace is not None:
                trace.close()
            raise
        print(f"[sim] /metrics on 127.0.0.1:{port}", file=sys.stderr,
              flush=True)
    kwargs = {}
    if registry is not None or trace is not None:
        kwargs = {
            # metrics=None + a trace writer -> the sampler records into
            # a private registry (SimMetrics' fallback).
            "metrics": registry,
            "metrics_stride": args.metrics_stride,
            "trace_writer": trace,
        }
    return registry, trace, server, kwargs


def _run_sim(args: argparse.Namespace, cfg) -> int:
    if args.host_native:
        # The native C fast-path: bit-identical to the device paths on
        # its (lean matching) domain, ~50x XLA-CPU — million-scale
        # convergence studies with no accelerator at all.
        from .sim import hostsim

        if args.shards:
            print("--host-native runs unsharded (single host)",
                  file=sys.stderr)
            return 2
        if not hostsim.supported(cfg):
            print(
                "--host-native needs the matching domain (lean or full "
                "profile): no --churn, --nodes a multiple of 128, "
                "--keys <= 127 and --keys * --nodes < 2^24 "
                "(sim.hostsim.supported)",
                file=sys.stderr,
            )
            return 2
        if cfg.track_heartbeats and args.max_rounds > 32_766:
            # The full profile's int16 heartbeat matrices cap the run
            # horizon; clamp up front rather than dying mid-run with
            # the kernel's RuntimeError after hours of compute.
            print(
                "--host-native full profile: clamping --max-rounds to "
                "32766 (int16 heartbeat horizon)",
                file=sys.stderr,
            )
            args.max_rounds = 32_766
        if not hostsim.available():
            print("native hostsim build failed (g++ unavailable?)",
                  file=sys.stderr)
            return 2
        _registry, trace, server, obs_kwargs = _make_telemetry(args)
        try:
            host = hostsim.HostSimulator(cfg, seed=args.seed, **obs_kwargs)
            converged = host.run_until_converged(max_rounds=args.max_rounds)
            telemetry_samples = host.flush_metrics()
        finally:
            if server is not None:
                server.stop_thread()
            if trace is not None:
                trace.close()
        # Same record shape as the device path (consumers key off
        # "engine", not a divergent schema); metrics recomputed from w
        # with convergence_metrics' semantics (all nodes alive here).
        import numpy as np

        # Reductions only — never an (N, N) float temporary: this path
        # exists for populations where w alone is ~10 GB, and on its
        # domain w <= keys_per_node always (no writes), so the device
        # path's clip is a no-op and min/mean commute with the divide.
        k = cfg.keys_per_node
        col_min = host.w.min(axis=0)
        metrics = {
            "converged_owners": int((col_min >= k).sum()),
            "all_converged": bool((col_min >= k).all()),
            "min_fraction": float(host.w.min()) / k,
            "mean_fraction": float(host.w.mean(dtype=np.float64)) / k,
            "alive_count": cfg.n_nodes,
        }
        record = {
            "nodes": args.nodes,
            "shards": 1,
            "engine": "host-native",
            "rounds_to_convergence": converged,
            "tick": host.tick,
            "metrics": metrics,
        }
        if telemetry_samples:
            record["telemetry_samples"] = len(telemetry_samples)
        print(json.dumps(record), flush=True)
        return 0 if converged is not None else 1

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    # Persistent XLA compilation cache (utils/xla_cache.py): reruns of
    # the same study skip the compile. AIOCLUSTER_XLA_CACHE overrides
    # the location ("off" disables); failures are non-fatal.
    from .utils.xla_cache import enable_persistent_cache

    enable_persistent_cache(
        log=lambda msg: print(f"[sim] {msg}", file=sys.stderr, flush=True)
    )
    from .sim import Simulator

    mesh = None
    if args.shards:
        from .parallel.mesh import make_mesh

        devices = jax.devices()
        if args.shards < 0:
            print(f"--shards {args.shards} must be positive", file=sys.stderr)
            return 2
        if args.shards > len(devices):
            print(
                f"--shards {args.shards} > {len(devices)} visible device(s)",
                file=sys.stderr,
            )
            return 2
        if args.nodes % args.shards:
            print(
                f"--nodes {args.nodes} must divide evenly into "
                f"--shards {args.shards}",
                file=sys.stderr,
            )
            return 2
        mesh = make_mesh(devices[: args.shards])
    _registry, trace, server, obs_kwargs = _make_telemetry(args)
    try:
        sim = Simulator(cfg, seed=args.seed, mesh=mesh, chunk=8, **obs_kwargs)
        converged = sim.run_until_converged(max_rounds=args.max_rounds)
        telemetry_samples = sim.flush_metrics()
    finally:
        if server is not None:
            server.stop_thread()
        if trace is not None:
            trace.close()
    m = {k: v.tolist() for k, v in sim.metrics().items()}
    record = {
        "nodes": args.nodes,
        "shards": args.shards or 1,
        "rounds_to_convergence": converged,
        "tick": sim.tick,
        "metrics": m,
    }
    if telemetry_samples:
        record["telemetry_samples"] = len(telemetry_samples)
    print(json.dumps(record), flush=True)
    return 0 if converged is not None else 1


def _run_twin(args: argparse.Namespace) -> int:
    """Replay → calibrate (→ autotune) from the CLI (docs/twin.md): the
    one-command form of the twin loop. Prints a JSON summary; exits
    nonzero when the held-out validation misses its stated tolerance or
    no candidate lane meets the SLO."""
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from . import twin

    def csv_list(text, cast):
        return None if text is None else [cast(x) for x in text.split(",")]

    # Flag-combination validation up front, before any work: candidate
    # lists / an FD budget without a deadline would otherwise be
    # silently dropped, and a deadline without candidates has no grid
    # to sweep — both are operator mistakes, not runnable requests.
    tuning_flags = [
        name for name, val in (
            ("--fanout", args.fanout),
            ("--phi", args.phi),
            ("--writes", args.writes),
            ("--fd-budget", args.fd_budget),
        ) if val is not None
    ]
    if args.deadline is None and tuning_flags:
        print(
            f"twin: {', '.join(tuning_flags)} require --deadline "
            "(the SLO the candidates are tuned against)",
            file=sys.stderr, flush=True,
        )
        return 2
    if args.deadline is not None and not (
        args.fanout or args.phi or args.writes
    ):
        print(
            "twin: --deadline needs at least one candidate list "
            "(--fanout/--phi/--writes) spanning two or more lanes",
            file=sys.stderr, flush=True,
        )
        return 2

    if args.check_drift is not None:
        # Drift-monitor mode (docs/twin.md, twin/drift.py): verdict a
        # FRESH trace against a STORED calibration — the cron-shaped
        # loop; exits 1 on drift so the cron alerts.
        cal = twin.load_calibration(args.check_drift)
        verdict = twin.check_drift(
            cal,
            args.trace,
            window=args.drift_window,
            tolerance=args.tolerance,
            seed=args.seed,
        )
        print(
            json.dumps(
                {
                    "trace": args.trace,
                    "calibration": args.check_drift,
                    "drift": verdict.to_dict(),
                }
            ),
            flush=True,
        )
        return 0 if verdict.ok else 1

    trace = twin.load_runtime_trace(args.trace)
    report = twin.replay(trace, seed=args.seed)
    cal = twin.fit_calibration(
        report,
        tolerance=0.35 if args.tolerance is None else args.tolerance,
    )
    if args.calibration_out:
        twin.save_calibration(args.calibration_out, cal)
    out = {
        "trace": trace.path,
        "n_nodes": trace.n_nodes,
        "trace_rounds": len(trace.rounds),
        "skipped_lines": trace.skipped,
        "sim_converged_round": report.sim_converged_round,
        "calibration": cal.to_dict(),
    }
    ok = cal.holdout_ok
    if args.deadline is not None:
        from .core.config import Config
        from .core.identity import NodeId

        slo = twin.SLO(
            convergence_deadline_s=args.deadline,
            fd_false_positive_budget=args.fd_budget,
        )
        # The CLI has no deployment Config to tune against; recommend
        # over a placeholder identity — the tunables are what matter.
        base = Config(
            node_id=NodeId(
                name="operator", gossip_advertise_addr=("127.0.0.1", 0)
            )
        )
        try:
            rec = twin.autotune(
                slo,
                cal,
                base,
                twin.lift_sim_config(trace),
                fanout=csv_list(args.fanout, int),
                phi_threshold=csv_list(args.phi, float),
                writes_per_round=csv_list(args.writes, int),
                seed=args.seed,
            )
            out["recommendation"] = rec.to_dict()
        except twin.AutotuneInfeasible as exc:
            out["autotune_infeasible"] = str(exc)
            out["lanes"] = exc.lanes
            ok = False
        except ValueError as exc:
            # e.g. a single-value candidate list (one lane is not a
            # sweep) — still report through the JSON contract.
            out["autotune_error"] = str(exc)
            ok = False
    print(json.dumps(out), flush=True)
    return 0 if ok else 1


def _run_fleet(args: argparse.Namespace) -> int:
    """Operator fleet view: fetch GET /fleet from any member's serve
    tier (stdlib urllib — the CLI must work on a box with nothing but
    the package installed) and render the table. ``--json`` passes the
    payload through for scripting."""
    from urllib.error import URLError
    from urllib.request import urlopen

    url = args.url.rstrip("/") + "/fleet"
    if args.stale_s is not None:
        url = f"{url}?stale_s={args.stale_s:g}"
    try:
        with urlopen(url, timeout=args.timeout) as resp:
            view = json.loads(resp.read().decode())
    except (URLError, OSError, ValueError) as exc:
        print(f"fleet: {url}: {exc}", file=sys.stderr, flush=True)
        return 2
    if args.json:
        print(json.dumps(view, sort_keys=True), flush=True)
        return 0
    head = (
        f"fleet via {view.get('self', '?')}  epoch={view.get('epoch')}  "
        f"known={view.get('known')}  covered={view.get('covered')}  "
        f"coverage={view.get('coverage_frac')}  "
        f"suspect={view.get('suspect')}"
    )
    if "staleness_p99_s" in view:
        head += (
            f"  staleness p50/p99/max="
            f"{view['staleness_p50_s']:g}/{view['staleness_p99_s']:g}"
            f"/{view['staleness_max_s']:g}s"
        )
    print(head, flush=True)
    rows = [("NODE", "LIVE", "HB", "STALE_S", "STATE", "P99_S")]
    for name in sorted(view.get("nodes", {})):
        entry = view["nodes"][name]
        digest = entry.get("digest") or {}
        if entry.get("suspect"):
            stale = "suspect"
        elif entry.get("staleness_s") is not None:
            stale = f"{entry['staleness_s']:g}"
        else:
            stale = "-"
        p99 = digest.get("p99")
        rows.append((
            name,
            "yes" if entry.get("live") else "no",
            str(entry.get("heartbeat_local", "-")),
            stale,
            str(digest.get("st", "-")),
            "-" if p99 is None else f"{p99:g}",
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for row in rows:
        print(
            "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip(),
            flush=True,
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m aiocluster_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    node = sub.add_parser("node", help="run one asyncio cluster node")
    node.add_argument("--name", required=True)
    node.add_argument("--listen", type=_parse_addr, required=True,
                      metavar="HOST:PORT")
    node.add_argument("--seed", type=_parse_addr, action="append",
                      default=[], metavar="HOST:PORT",
                      help="seed node address (repeatable)")
    node.add_argument("--cluster-id", default="default-cluster")
    node.add_argument("--interval", type=float, default=1.0)
    node.add_argument("--set", type=_parse_kv, action="append",
                      metavar="KEY=VALUE", help="initial key (repeatable)")
    node.add_argument("--metrics-port", type=int, default=None,
                      metavar="PORT",
                      help="serve Prometheus text on 127.0.0.1:PORT"
                      "/metrics (0 = ephemeral port, printed to stderr)")
    node.add_argument("--trace-file", default=None, metavar="PATH",
                      help="append per-round JSONL trace events to PATH")

    sim = sub.add_parser("sim", help="run a tensor-sim convergence study")
    sim.add_argument("--nodes", type=int, default=1024)
    sim.add_argument("--keys", type=int, default=16)
    sim.add_argument("--fanout", type=int, default=3)
    sim.add_argument("--mtu", type=int, default=None,
                     help="per-exchange budget as a wire MTU in bytes "
                     "(default: the reference's 65,507)")
    sim.add_argument("--churn", type=float, default=0.0,
                     help="per-round death probability (revival = 4x)")
    sim.add_argument("--grace", type=int, default=40,
                     help="dead-node grace in rounds (with --churn)")
    sim.add_argument("--lean", action="store_true",
                     help="convergence-only profile (no FD matrices)")
    sim.add_argument("--seed", type=int, default=0)
    sim.add_argument("--max-rounds", type=int, default=10_000)
    sim.add_argument("--cpu", action="store_true",
                     help="pin the CPU backend")
    sim.add_argument("--shards", type=int, default=0,
                     help="column-shard the owner axis over this many "
                     "devices (the BASELINE config-5 shape; 0 = one "
                     "device, no mesh)")
    sim.add_argument("--metrics-port", type=int, default=None,
                     metavar="PORT",
                     help="serve Prometheus text on 127.0.0.1:PORT"
                     "/metrics from a daemon thread (0 = ephemeral port)")
    sim.add_argument("--trace-file", default=None, metavar="PATH",
                     help="append sampled sim_round JSONL events to PATH")
    sim.add_argument("--metrics-stride", type=int, default=64,
                     help="rounds between metric samples (device metrics "
                     "are buffered un-synced and flushed at the end; "
                     "default 64)")
    sim.add_argument("--host-native", action="store_true",
                     help="run the native C host fast-path (bit-"
                     "identical on the matching domain — lean, or the "
                     "full FD profile at int16/bf16 scale dtypes; no "
                     "churn/shards)")

    twin = sub.add_parser(
        "twin",
        help="replay a recorded runtime trace, fit a calibration, "
        "optionally autotune against an SLO (docs/twin.md)",
    )
    twin.add_argument("--trace", required=True, metavar="PATH",
                      help="twin-grade JSONL trace (Cluster.trace_rounds)")
    twin.add_argument("--calibration-out", default=None, metavar="PATH",
                      help="write the fitted CalibrationRecord JSON here")
    twin.add_argument("--seed", type=int, default=0)
    twin.add_argument("--tolerance", type=float, default=None,
                      help="held-out validation tolerance recorded in "
                      "(and gated by) the calibration (default 0.35)")
    twin.add_argument("--deadline", type=float, default=None,
                      metavar="SECONDS",
                      help="SLO convergence deadline; with candidate "
                      "lists below, runs the autotuner")
    twin.add_argument("--fd-budget", type=float, default=None,
                      help="SLO failure-detector false-positive budget")
    twin.add_argument("--fanout", default=None,
                      help="comma-separated fanout candidates")
    twin.add_argument("--phi", default=None,
                      help="comma-separated phi-threshold candidates")
    twin.add_argument("--writes", default=None,
                      help="comma-separated writes-per-round candidates")
    twin.add_argument("--check-drift", default=None, metavar="CALIBRATION",
                      help="drift-monitor mode: verdict --trace against "
                      "this stored CalibrationRecord (twin/drift.py); "
                      "exits 1 on drift")
    twin.add_argument("--drift-window", type=int, default=None,
                      metavar="ROUNDS",
                      help="rolling window for --check-drift (default: "
                      "the stored record's fit window)")
    twin.add_argument("--cpu", action="store_true",
                      help="pin the CPU backend")

    fleet = sub.add_parser(
        "fleet",
        help="render any member's fleet view (GET /fleet, obs/fleet.py)",
    )
    fleet.add_argument("--url", required=True, metavar="URL",
                       help="base URL of a member's serve tier, e.g. "
                       "http://127.0.0.1:8080")
    fleet.add_argument("--stale-s", type=float, default=None, dest="stale_s",
                       metavar="SECONDS",
                       help="only entries at most this stale (?stale_s=)")
    fleet.add_argument("--timeout", type=float, default=5.0)
    fleet.add_argument("--json", action="store_true",
                       help="print the raw JSON payload instead of a table")

    args = parser.parse_args(argv)
    if args.command == "node":
        try:
            return asyncio.run(_run_node(args))
        except KeyboardInterrupt:
            return 0
    if args.command == "twin":
        return _run_twin(args)
    if args.command == "fleet":
        return _run_fleet(args)
    try:
        cfg = _sim_config(args)
    except ValueError as exc:  # bad --mtu/--nodes/--grace combinations
        parser.error(str(exc))
    return _run_sim(args, cfg)


if __name__ == "__main__":
    sys.exit(main())
