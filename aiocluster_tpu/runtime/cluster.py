"""The Cluster runtime: lifecycle, gossip rounds, KV API, hooks, snapshots.

Parity: reference server.py:74-653 (``Cluster``), decomposed over the
engine/transport/hooks/peers/ticker modules. The public surface (method
names, constructor signature, snapshot shape) matches the reference so
applications port without changes.
"""

from __future__ import annotations

import asyncio
from asyncio import StreamReader, StreamWriter
from collections.abc import Awaitable, Callable, Sequence
from contextlib import suppress
from dataclasses import dataclass
from datetime import timedelta
from random import Random
from types import TracebackType

from ..core.cluster_state import ClusterState
from ..core.config import Config
from ..core.failure import FailureDetector
from ..core.identity import Address, NodeId
from ..core.kvstate import NodeState
from ..core.messages import (
    Ack,
    BadCluster,
    Delta,
    Digest,
    Leave,
    NodeDigest,
    Packet,
    Syn,
    SynAck,
    TraceContext,
)
from ..core.values import VersionedValue
from ..obs.fleet import (
    TELEMETRY_KEY,
    assemble_fleet_view,
    build_fleet_entry,
    encode_health_digest,
    round_latency_percentiles,
)
from ..obs.flightrec import FlightRecorder
from ..obs.registry import MetricsRegistry, default_registry
from ..obs.trace import TraceWriter
from ..utils.clock import resolve_clock, utc_now
from ..utils.logging import node_logger
from ..wire import native as wire_native
from ..wire.proto import encode_trace_context
from .engine import GossipEngine
from .hooks import HookDispatcher, HookStats
from .peers import select_gossip_targets
from .pool import ConnectionPool, PooledConnection
from .ticker import Ticker
from .transport import GossipTransport

# Bound on how far a Leave announcement's claimed FINAL heartbeat may
# exceed our own knowledge of the leaver when recording the departed
# hold. An honest final value leads any peer's view by at most the
# in-flight window (a handful of rounds); an attacker's inflated claim
# (heartbeat=2**60 would otherwise make the hold unliftable and
# quarantine a LIVE victim until dead-node GC — the one field
# handle_leave's delta guards don't cover) is capped so the victim's
# real heartbeats walk past the hold and phi restores it within a
# bounded window.
LEAVE_HB_SLACK = 1000

# Failure modes meaning "the peer ended the connection" — on a REUSED
# pooled connection these are expected (close-per-handshake peers, idle
# timeouts racing a borrow) and warrant one retry on a fresh dial.
_PEER_CLOSED_ERRORS = (
    asyncio.IncompleteReadError,
    ConnectionResetError,
    BrokenPipeError,
    ConnectionAbortedError,
)

KeyChangeCallback = Callable[
    [NodeId, str, VersionedValue | None, VersionedValue], Awaitable[None]
]
NodeEventCallback = Callable[[NodeId], Awaitable[None]]


@dataclass(frozen=True, slots=True)
class ClusterSnapshot:
    """A point-in-time, *detached* view of the cluster.

    ``epoch`` is the monotonic state generation
    (``ClusterState.digest_epoch``) at capture: equal epochs imply
    identical state, which is what the serve tier keys its
    encode-once-per-epoch payload cache (and HTTP ETags) on. The node
    states are deep copies — mutating the fleet after ``snapshot()``
    never mutates an already-taken snapshot.
    """

    cluster_id: str
    self_node_id: NodeId
    node_states: dict[NodeId, NodeState]
    live_nodes: list[NodeId]
    dead_nodes: list[NodeId]
    epoch: int = 0


class Cluster:
    """One gossip cluster member: owns its keyspace, replicates peers'."""

    def __init__(
        self,
        config: Config,
        initial_key_values: dict[str, str] | None = None,
        rng: Random | None = None,
        metrics: MetricsRegistry | None = None,
        trace: TraceWriter | None = None,
    ) -> None:
        self._rng = rng if rng is not None else Random()
        # The one clock this node reads (utils/clock.py): ambient —
        # real time by default, the loop's virtual clock when running
        # under vtime (docs/virtual-time.md). Round durations, RTT
        # samples, pool idle stamps, flight-recorder timestamps and
        # provenance t_mono all come from here so they compress (and
        # replay) together.
        self._clock = resolve_clock(None)

        # Telemetry (obs/): every subsystem reports through one registry —
        # the process default unless the caller injects its own (tests and
        # multi-node-per-process setups pass per-cluster registries).
        # ``trace`` optionally records one JSONL event per gossip round
        # and per membership transition.
        self._metrics = metrics if metrics is not None else default_registry()
        self._trace = trace
        # Flight recorder (obs/flightrec.py): ALWAYS on — a bounded
        # in-memory ring of recent notable events (handshake outcomes,
        # FD flips, breaker transitions, guard rejections, applies,
        # lifecycle), dumped post-mortem via flight_record() and the
        # serve tier's /debug/flightrec. note() is two clock reads and
        # a deque append; nothing formats until a dump is asked for.
        self._flightrec = FlightRecorder(clock=self._clock)
        self._lifecycle_events = self._metrics.counter(
            "aiocluster_lifecycle_events_total",
            "Node lifecycle events: rejoin_clean (warm rejoin, previous "
            "generation kept), rejoin_unclean (keyspace restored, "
            "generation bumped), leave_initiated, leave_announced (one "
            "per peer successfully notified), leave_received (a peer's "
            "departure announcement applied)",
            labels=("event",),
        )

        # Durable node state (docs/robustness.md "Durability &
        # lifecycle"): recovery runs BEFORE anything reads
        # config.node_id. A store proving a clean shutdown lets this
        # boot resume the previous incarnation (same generation — its
        # keyspace was fully flushed, so its version counter is safe to
        # continue); an unclean store bumps the generation, seeded by
        # the store's durable guard so even a regressed wall clock
        # cannot reissue an old one. ``Config.persistence=None`` builds
        # none of this — the reference's amnesiac boot, byte-identical.
        self._persist = None
        self._recovered = None
        self._persist_clean_on_close = True
        self._snapshotting = False
        if config.persistence is not None:
            from dataclasses import replace as _dc_replace

            from ..core.identity import next_generation_id
            from .persist import NodeStore

            self._persist = NodeStore(
                config.persistence, metrics=self._metrics
            )
            self._recovered = self._persist.load()
            if self._recovered is not None:
                if self._recovered.clean:
                    generation = self._recovered.generation
                    self._lifecycle_events.labels("rejoin_clean").inc()
                    self._flightrec.note(
                        "lifecycle", event="rejoin_clean",
                        generation=generation,
                    )
                else:
                    # load() already seeded the guard with the store's
                    # floor, so this is strictly above every generation
                    # the store ever recorded.
                    generation = next_generation_id()
                    self._lifecycle_events.labels("rejoin_unclean").inc()
                    self._flightrec.note(
                        "lifecycle", event="rejoin_unclean",
                        generation=generation,
                    )
                config = _dc_replace(
                    config,
                    node_id=_dc_replace(
                        config.node_id, generation_id=generation
                    ),
                )
        self._config = config
        self._log = node_logger(config.node_id.long_name())
        self._round_seconds = self._metrics.histogram(
            "aiocluster_round_seconds",
            "Wall-clock duration of one initiated gossip round",
        )
        self._peer_selection = self._metrics.counter(
            "aiocluster_peer_selection_total",
            "Gossip targets chosen per round, by kind (live/dead/seed)",
            labels=("kind",),
        )
        self._phi_hist = self._metrics.histogram(
            "aiocluster_fd_phi",
            "Phi-accrual suspicion samples across peers",
            buckets=(0.5, 1.0, 2.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0),
        )
        self._fd_transitions = self._metrics.counter(
            "aiocluster_fd_transitions_total",
            "Failure-detector membership transitions, by new state",
            labels=("to",),
        )
        self._live_gauge = self._metrics.gauge(
            "aiocluster_live_nodes", "Peers currently believed live"
        )
        self._dead_gauge = self._metrics.gauge(
            "aiocluster_dead_nodes", "Peers currently believed dead"
        )

        self._cluster_state = ClusterState(seed_addrs=set(config.seed_nodes))
        self._failure_detector = FailureDetector(config.failure_detector)
        self._hooks = HookDispatcher(
            config.hook_queue_maxsize,
            drain_on_shutdown=config.drain_hooks_on_shutdown,
            shutdown_timeout=config.hook_shutdown_timeout,
            log=self._log,
            metrics=self._metrics,
        )
        self._engine = GossipEngine(
            config,
            self._cluster_state,
            self._failure_detector,
            on_key_change=self._emit_key_change,
            metrics=self._metrics,
            flightrec=self._flightrec,
            clock=self._clock,
        )
        # Zero-copy wire data plane (wire/segments.py): when on (the
        # default), handshake steps below route through the
        # scatter-gather parts paths; False keeps every encode/frame/
        # decode byte- and path-identical to the reference shape.
        self._wire_fastpath = config.wire_fastpath
        transport = GossipTransport(
            max_payload_size=config.max_payload_size,
            connect_timeout=config.connect_timeout,
            read_timeout=config.read_timeout,
            write_timeout=config.write_timeout,
            tls_server_context=config.tls_server_context,
            tls_client_context=config.tls_client_context,
            tls_server_hostname=config.tls_server_hostname,
            metrics=self._metrics,
            wire_fastpath=config.wire_fastpath,
        )
        # Deterministic fault injection (docs/faults.md): only an
        # EFFECTIVE plan — the configured fault_plan plus
        # heterogeneity's derived WAN LinkFaults — constructs the
        # controller/wrapper; with neither the transport above is used
        # as-is, byte-identical to before.
        self._fault_controller = None
        effective_plan = config.fault_plan
        if config.heterogeneity is not None:
            from ..faults.plan import with_extra_links

            effective_plan = with_extra_links(
                effective_plan, config.heterogeneity.wan_link_faults()
            )
        if effective_plan is not None:
            from ..faults.runtime import FaultController, FaultyTransport

            self._fault_controller = FaultController(
                effective_plan,
                config.node_id.name,
                metrics=self._metrics,
            )
            transport = FaultyTransport(
                transport, self._fault_controller, self._peer_label
            )
        self._transport = transport
        # Cadence classes (docs/faults.md "heterogeneity"): this node's
        # gossip interval is scaled by its class, derived from the same
        # stable name coordinate the fault plan uses — the runtime
        # analogue of the sim's per-tick initiator mask.
        self.effective_gossip_interval = config.gossip_interval
        # Zone lookups are pure functions of the (immutable) node name,
        # so the per-peer zones accrete in one cache instead of
        # re-hashing the whole membership every round (departed
        # addresses linger harmlessly: reads are keyed by live peers).
        self._zone_cache: dict[Address, int] = {}
        self._self_zone: int | None = None
        if config.heterogeneity is not None:
            self.effective_gossip_interval *= (
                config.heterogeneity.gossip_every_of_name(
                    config.node_id.name
                )
            )
            if config.heterogeneity.zone_bias > 0:
                self._self_zone = config.heterogeneity.zone_of_name(
                    config.node_id.name
                )
        # Overload & degradation control (docs/robustness.md): per-peer
        # EWMA RTT -> adaptive timeouts on the gossip path, plus a
        # per-peer circuit breaker quarantining broken peers from the
        # target draw. Constructed only when a flag is on — with both
        # off, every path below is byte-identical to the fixed-constant
        # reference posture. Backoff windows are configured in
        # effective-gossip-interval units, so the quarantine cadence
        # follows this node's actual round clock.
        self._health = None
        if config.adaptive_timeouts or config.circuit_breaker:
            from .health import HealthTracker

            self._health = HealthTracker(
                adaptive=config.adaptive_timeouts,
                breaker=config.circuit_breaker,
                # An injected cluster rng is the determinism signal
                # (ChaosHarness virtual-time soaks): derive the breaker
                # backoff rng from it so the whole node is one seed.
                # Default (rng=None) keeps the tracker's own Random().
                rng=(
                    Random(self._rng.getrandbits(64))
                    if rng is not None
                    else None
                ),
                k=config.adaptive_timeout_k,
                min_timeout=config.adaptive_timeout_min,
                max_timeout=config.read_timeout,
                failure_threshold=config.breaker_failure_threshold,
                base_backoff=(
                    config.breaker_base_backoff_intervals
                    * self.effective_gossip_interval
                ),
                max_backoff=(
                    config.breaker_max_backoff_intervals
                    * self.effective_gossip_interval
                ),
                metrics=self._metrics,
                on_transition=self._note_breaker_transition,
            )
        self._pool = ConnectionPool(
            self._transport.connect,
            max_idle_per_peer=(
                config.pool_max_idle_per_peer
                if config.persistent_connections
                else 0
            ),
            idle_timeout=config.pool_idle_timeout,
            metrics=self._metrics,
            clock=self._clock,
            on_dial=(
                None
                if self._health is None or not config.adaptive_timeouts
                else lambda key, dt: self._health.record_rtt(
                    (key[0], key[1]), dt
                )
            ),
        )
        # Jitter scales with the EFFECTIVE interval: a slow-cadence
        # class desynchronized over a fraction of the base interval
        # would still fire near-simultaneously within its own period.
        initial_delay = (
            self._rng.uniform(
                0, config.gossip_jitter * self.effective_gossip_interval
            )
            if config.gossip_jitter > 0
            else 0.0
        )
        self._ticker = Ticker(
            self._gossip_round,
            self.effective_gossip_interval,
            initial_delay=initial_delay,
            on_error=lambda exc: self._log.exception(f"Gossip round error: {exc}"),
            metrics=self._metrics,
            metrics_label="gossip",
        )
        self._gossip_semaphore = asyncio.Semaphore(
            max(1, config.max_concurrent_gossip)
        )

        self._on_node_join: list[NodeEventCallback] = []
        self._on_node_leave: list[NodeEventCallback] = []
        self._on_key_change: list[KeyChangeCallback] = []
        self._prev_live: set[NodeId] = set()
        # Peers that announced a graceful departure (Leave), with the
        # reason and the heartbeat we held for them at that moment:
        # _update_liveness keeps them dead (no phi re-evaluation) until
        # fresh heartbeat EVIDENCE proves a comeback — phi alone would
        # resurrect them for the rest of the sampling window.
        self._departed: dict[NodeId, tuple[str, int]] = {}
        # Epidemic relays of departure announcements (one per FIRST
        # receipt): retained so the tasks are not GC'd mid-flight and
        # can be cancelled at close.
        self._leave_forwards: set[asyncio.Task] = set()

        self._server: asyncio.Server | None = None
        self._inbound: set[StreamWriter] = set()
        self._codec_warmup: asyncio.Task | None = None
        self._started = False
        self._closing = False

        # Twin-grade round tracing (docs/twin.md): attached by
        # trace_rounds(), off by default. ``_twin_round`` is this node's
        # own monotone round index (the replay aligner's per-node clock);
        # the prev_* cursors difference the engine's cumulative
        # reconciliation totals into per-round figures.
        self._twin_trace: TraceWriter | None = None
        self._twin_round = 0
        self._twin_prev_sent = 0
        self._twin_prev_applied = 0
        self._last_phi_max = 0.0

        # Propagation provenance (obs/prov.py, docs/observability.md):
        # attached by trace_provenance(), off by default — detached
        # clusters run byte-identical paths (the engine's prov branches
        # and the per-handshake peer-name resolution below are all
        # gated on this).
        self._prov: TraceWriter | None = None

        # Wire-level span context (docs/observability.md "Fleet
        # telemetry"): with ``Config.trace_context`` on, every
        # Syn/SynAck/Ack carries envelope field 7 (sender name +
        # initiator-chosen handshake id) appended AFTER the cached
        # parts — the per-digest-epoch Syn caches and shared payloads
        # never see the per-handshake bytes. Off (the default): nothing
        # is appended, frames byte-identical to the reference.
        self._trace_context = config.trace_context
        self._next_handshake_id = 0

        # Gossip-borne self-telemetry (obs/fleet.py): with
        # ``Config.telemetry_interval`` set, `_gossip_round` folds a
        # compact health digest into this node's own keyspace every
        # ``_telemetry_every_rounds`` rounds — ONE owner write per
        # interval, so the content epoch bumps at most once per
        # interval and SnapshotCache dedup stays effective. None (the
        # default) publishes nothing and tracks nothing.
        self._telemetry_interval = config.telemetry_interval
        self._telemetry_every_rounds = 1
        self._round_durations = None
        if self._telemetry_interval is not None:
            from collections import deque

            self._telemetry_every_rounds = max(
                1,
                round(
                    self._telemetry_interval
                    / max(self.effective_gossip_interval, 1e-9)
                ),
            )
            self._round_durations = deque(maxlen=128)
        # First telemetry-eligible round publishes immediately (the
        # fleet should not wait a full interval to see a booted node).
        self._rounds_since_telemetry = self._telemetry_every_rounds
        self._fleet_publishes = self._metrics.counter(
            "aiocluster_fleet_telemetry_publishes_total",
            "Self-telemetry digests folded into this node's own keyspace",
        )
        self._fleet_view_nodes = self._metrics.gauge(
            "aiocluster_fleet_view_nodes",
            "Known nodes in the most recently assembled fleet view",
        )
        self._fleet_suspects = self._metrics.counter(
            "aiocluster_fleet_view_suspect_total",
            "Fleet-view entries whose advertised heartbeat exceeded the "
            "locally known watermark (marked suspect, not trusted)",
        )

        # Seed our own state: the recovered keyspace (when a store was
        # restored), one heartbeat, then initial keys (idempotent — a
        # recovered live value is not re-written).
        if self._recovered is not None:
            self._install_recovered_state()
        me = self.self_node_state()
        me.inc_heartbeat()
        for key, value in (initial_key_values or {}).items():
            me.set(key, value)

    def _install_recovered_state(self) -> None:
        """Wire the recovered store into the fresh ClusterState: our own
        keyspace at its persisted versions (and, on a clean rejoin, the
        previous incarnation's heartbeat, so peers — who only credit
        increases — see the same counter resume), plus the persisted
        peer view as HINTS (they re-verify via normal digests; a peer
        restarted with a newer generation is a different NodeId and
        wins exactly as before)."""
        rec = self._recovered
        own = NodeState(
            self._config.node_id,
            heartbeat=rec.heartbeat if rec.clean else 0,
            key_values=dict(rec.key_values),
            max_version=rec.max_version,
            last_gc_version=rec.last_gc_version,
        )
        self._cluster_state.install_node_state(own)
        if self._config.persistence.restore_peers:
            for peer in rec.peers:
                if peer.node == self._config.node_id:
                    continue
                # An unclean reboot bumped our generation: our own OLD
                # incarnation must not be reinstalled as a "peer" — its
                # state would shadow-advertise until the FD aged it out.
                if peer.node.name == self._config.node_id.name:
                    continue
                self._cluster_state.install_node_state(peer)

    # -- lifecycle ------------------------------------------------------------

    async def __aenter__(self) -> "Cluster":
        await self.start()
        return self

    async def __aexit__(
        self,
        et: type[BaseException] | None = None,
        exc: BaseException | None = None,
        tb: TracebackType | None = None,
    ) -> None:
        await self.close()

    async def start(self) -> None:
        if self._started:
            return
        host, port = self._config.node_id.gossip_advertise_addr
        self._log.debug(
            f"Booting {self.self_node_id.long_name()} "
            f"[{self._config.cluster_id}]"
        )
        # Latch _started BEFORE the bind suspends: a second start()
        # arriving while the bind is in flight must see the latch and
        # return, not bind twice. A failed boot (e.g. EADDRINUSE) rolls
        # the latch back so the cluster stays retryable instead of
        # permanently half-dead.
        self._started = True
        try:
            self._server = await self._transport.start_server(
                host, port, self._handle_connection
            )
        except BaseException:
            self._started = False
            raise
        # Warm the native bulk codec in the background: its first use
        # otherwise shells out to g++ inside a gossip handshake, and
        # awaiting it here would serialize cold-cache boots behind the
        # compile. Created only after a successful bind so a failed boot
        # (where close() early-returns) cannot orphan the task; the codec
        # no-ops to pure Python until the build lands.
        if self._codec_warmup is None:
            self._codec_warmup = asyncio.create_task(
                asyncio.to_thread(wire_native.warmup)
            )
        self._flightrec.note(
            "lifecycle", event="start", node=self._config.node_id.name,
            generation=self._config.node_id.generation_id,
        )
        if self._persist is not None and self._recovered is None:
            # A store with intent-log records but no snapshot cannot be
            # recovered (no generation to anchor them to) — seed the
            # snapshot at first boot so every journaled write is
            # anchored from the start.
            await self._write_persist_snapshot()
        self._hooks.start()
        self._ticker.start()

    async def _write_persist_snapshot(self) -> None:
        """One atomic store snapshot off-loop. Copies are taken
        synchronously (no await between copy and dispatch), so the
        written snapshot is a consistent point-in-time view even while
        gossip keeps mutating the live state."""
        if self._persist is None or self._snapshotting:
            return
        self._snapshotting = True
        try:
            own = self.self_node_state().copy()
            peers = None
            if self._config.persistence.restore_peers:
                peers = [
                    ns.copy()
                    for nid, ns in self._cluster_state.node_states().items()
                    if nid != self.self_node_id
                ]
            # Rotate the intent log SYNCHRONOUSLY with the copies:
            # writes journaled after this instant postdate the copied
            # state and must survive the snapshot (runtime/persist.py
            # begin_snapshot). The sequence makes overlapping writer
            # threads (a shutdown-orphaned one racing close()'s final
            # snapshot) last-copy-wins, never last-thread-wins.
            seq = self._persist.begin_snapshot()
            await asyncio.to_thread(
                self._persist.write_snapshot,
                own,
                self.self_node_id.generation_id,
                peers,
                seq,
            )
        except Exception as exc:
            # A failed snapshot must never take the node down — the
            # store just stays one interval staler.
            self._log.warning(f"persist snapshot failed: {exc!r}")
        finally:
            self._snapshotting = False

    async def close(self) -> None:
        if self._closing or not self._started:
            return
        self._closing = True
        self._flightrec.note(
            "lifecycle", event="close", clean=self._persist_clean_on_close
        )
        await self._ticker.stop()
        # Stop responding BEFORE the persistence flush: an inbound
        # handshake still being served would bump our heartbeat after
        # the clean marker sampled its "final" value — and advertise
        # the higher one to peers, who only credit INCREASES, leaving
        # the clean rejoin below its own floor for several rounds.
        await self._stop_server()
        if self._persist is not None:
            if self._persist_clean_on_close:
                # Graceful close: flush the final snapshot, then — and
                # only then — the clean marker. The marker is the proof
                # the next boot needs to keep this generation; a crash
                # between the two reads as unclean, which is correct
                # (the snapshot may predate the crash's last writes).
                await self._write_persist_snapshot()
                try:
                    await asyncio.to_thread(
                        self._persist.write_clean_marker,
                        self.self_node_id.generation_id,
                        self.self_node_state().heartbeat,
                    )
                except Exception as exc:
                    self._log.warning(f"clean marker write failed: {exc!r}")
            self._persist.close()
        # Swap the handle out BEFORE awaiting the join: a concurrent
        # close() (or a start() racing shutdown) must see None at once,
        # not cancel/await a task another closer already owns.
        warmup, self._codec_warmup = self._codec_warmup, None
        if warmup is not None:
            # Don't wait for a cold-cache native build (g++, up to 120s)
            # whose result nobody needs anymore — cancel and move on; the
            # orphaned compile thread finishes harmlessly.
            warmup.cancel()
            try:
                await warmup
            except asyncio.CancelledError:  # noqa: ACT013 -- absorbing the cancel we just issued
                # Our own cancel() surfacing. If close() itself was
                # cancelled in the same window, that cancellation
                # re-raises at the next await point (3.10 has no
                # Task.uncancel to tell the two apart).
                pass
            except Exception as exc:
                # A failed warmup build is harmless (the codec no-ops to
                # pure Python) — but say so once instead of eating it.
                self._log.debug(f"native codec warmup failed: {exc!r}")
        # Ticker is stopped, so no new borrows: close the idle pool
        # before the server so peers see orderly FINs, not RSTs.
        await self._pool.close()
        for task in list(self._leave_forwards):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:  # noqa: ACT013 -- absorbing the cancel we just issued; terminal join at close
                pass
            except Exception as exc:
                # The relay is best-effort, but a swallowed failure here
                # hid real teardown bugs before — leave a trace.
                self._log.debug(f"leave relay failed: {exc!r}")
        await self._stop_server()
        await self._hooks.stop()

    async def _stop_server(self) -> None:
        """Stop accepting and serving handshakes (idempotent). Split out
        of close() because ``leave()`` must stop responding BEFORE it
        announces: the announced final heartbeat is only final if no
        later inbound handshake can bump the counter."""
        # Swap-to-local before any await: close() and leave() both call
        # this, and the second caller must see None immediately rather
        # than close an already-closing server after a stale guard read.
        server, self._server = self._server, None
        if server is None:
            return
        server.close()
        # Persistent inbound channels may be parked waiting for their
        # next Syn; close them so the handler tasks finish now rather
        # than lingering for the idle window (on 3.12+ wait_closed
        # would block on them). Each handler's finally joins its own
        # writer; the join here covers a handler that already left.
        for writer in list(self._inbound):
            writer.close()
            with suppress(Exception):
                await writer.wait_closed()
        await server.wait_closed()

    async def shutdown(self) -> None:
        await self.close()

    async def abort(self) -> None:
        """Close WITHOUT the graceful-shutdown persistence flush: no
        final snapshot, no clean marker — the process-kill path the
        chaos harness uses to model a real crash. With persistence off
        this is exactly ``close()``."""
        self._persist_clean_on_close = False
        await self.close()

    async def leave(self, reason: str = "leave") -> None:
        """Graceful leave/drain (docs/robustness.md): stop initiating
        gossip, flush the intent log into a final snapshot, best-effort
        push a final delta of our own keyspace plus a departure
        announcement to up to ``gossip_count`` live peers (they move us
        to dead-with-reason immediately — no phi window to wait out),
        write the clean marker, then close. Every step is best-effort:
        a dead peer cannot block a drain."""
        if self._closing or not self._started:
            await self.close()
            return
        self._lifecycle_events.labels("leave_initiated").inc()
        self._flightrec.note("lifecycle", event="leave", reason=reason)
        # 1. Stop initiating AND responding (close() repeats both
        #    harmlessly). Stopping the responder freezes our heartbeat —
        #    the announcement below carries the FINAL value, so no
        #    in-flight digest can ever look like fresher evidence and
        #    resurrect us in a peer's view.
        await self._ticker.stop()
        await self._stop_server()
        # 2. The final delta: our own keyspace, packed under the MTU.
        #    Built by the normal packer against a digest that claims the
        #    peer knows everything EXCEPT us — so only our node delta is
        #    emitted, MTU-bounded, in version order.
        digest = Digest(
            {
                nid: ns.digest()
                for nid, ns in self._cluster_state.node_states().items()
            }
        )
        digest.node_digests[self.self_node_id] = NodeDigest(
            self.self_node_id, 0, 0, 0
        )
        delta = self._cluster_state.compute_partial_delta_respecting_mtu(
            digest, self._config.max_payload_size, set()
        )
        packet = Packet(
            self._config.cluster_id,
            Leave(
                self.self_node_id,
                delta,
                reason,
                heartbeat=self.self_node_state().heartbeat,
            ),
        )
        # 3. Announce to live peers (fanout = gossip_count), fresh
        #    connections so a stale pooled channel cannot eat the only
        #    announcement a peer would have received. Liveness is an
        #    ORDERING, not a filter: a node draining before its phi
        #    detector warmed up (liveness needs interval samples;
        #    replication does not) has an empty live set but perfectly
        #    reachable known peers — announcing to nobody would leave
        #    the whole fleet to the phi window.
        live = [
            n.gossip_advertise_addr
            for n in self._failure_detector.live_nodes()
        ]
        self._rng.shuffle(live)
        seen = set(live)
        known = [
            n.gossip_advertise_addr
            for n in self._cluster_state.nodes()
            if n != self.self_node_id
            and n.gossip_advertise_addr not in seen
        ]
        self._rng.shuffle(known)
        targets = live + known
        announced = await self._announce_packet(
            packet, targets[: max(1, self._config.gossip_count)]
        )
        self._lifecycle_events.labels("leave_announced").inc(announced)
        # 4. Graceful close: final snapshot + clean marker (persistence
        #    on), orderly teardown either way.
        await self.close()

    async def _announce_packet(
        self, packet: Packet, targets: list[Address]
    ) -> int:
        """Best-effort one-shot delivery of ``packet`` to each target —
        CONCURRENTLY, so one dead peer costs its own connect timeout,
        not a serial stall for everyone behind it (a rolling deploy has
        several nodes down at once; detection latency is the whole
        point of the announcement). Returns how many deliveries
        succeeded. Fresh connections: a stale pooled channel must not
        eat the only announcement a peer would have received."""
        tls_names = {
            n.gossip_advertise_addr: n.tls_name
            for n in self._cluster_state.nodes()
        }

        async def one(host: str, port: int) -> bool:
            writer = None
            try:
                _reader, writer = await self._transport.connect(
                    host, port, tls_names.get((host, port))
                )
                await self._transport.write_packet(writer, packet)
                return True
            except Exception as exc:
                self._log.debug(
                    f"announcement to {host}:{port} failed: {exc}"
                )
                return False
            finally:
                if writer is not None:
                    writer.close()
                    with suppress(Exception):
                        await writer.wait_closed()

        if not targets:
            return 0
        results = await asyncio.gather(
            *(one(host, port) for host, port in targets)
        )
        return sum(results)

    # -- observable surface ---------------------------------------------------

    @property
    def self_node_id(self) -> NodeId:
        return self._config.node_id

    def self_node_state(self) -> NodeState:
        return self._cluster_state.node_state_or_default(self._config.node_id)

    def live_nodes(self) -> Sequence[NodeId]:
        return [self.self_node_id, *self._failure_detector.live_nodes()]

    def dead_nodes(self) -> Sequence[NodeId]:
        return self._failure_detector.dead_nodes()

    def snapshot(self) -> ClusterSnapshot:
        return ClusterSnapshot(
            cluster_id=self._config.cluster_id,
            self_node_id=self.self_node_id,
            node_states=self._cluster_state.node_states_copy(),
            live_nodes=self._failure_detector.live_nodes(),
            dead_nodes=self._failure_detector.dead_nodes(),
            epoch=self._cluster_state.digest_epoch,
        )

    def state_epoch(self) -> int:
        """The monotonic state generation (``ClusterState.digest_epoch``):
        bumps on every digest-field or membership change, never
        regresses. Equal epochs ⇒ identical state — the int the serve
        tier compares before deciding whether anything needs encoding."""
        return self._cluster_state.digest_epoch

    def node_states_view(self) -> dict[NodeId, NodeState]:
        """The *live* per-node states (shallow dict copy, uncopied
        NodeState refs) for O(changes) synchronous readers — the serve
        tier's delta scans. Read-only by contract: callers must not
        mutate, and must not hold it across an await."""
        return self._cluster_state.node_states()

    def hook_stats(self) -> HookStats:
        return self._hooks.stats()

    @property
    def is_closed(self) -> bool:
        """True once close() has begun (or the cluster never started) —
        what the serve tier's /healthz turns into a 503."""
        return self._closing or not self._started

    @property
    def health(self):
        """The HealthTracker driving adaptive timeouts and circuit
        breaking (None when both ``Config.adaptive_timeouts`` and
        ``Config.circuit_breaker`` are off)."""
        return self._health

    def health_summary(self) -> dict:
        """Degraded-state report (docs/robustness.md): FD liveness plus
        the overload layer's current posture — what /healthz serves."""
        now = utc_now()
        phis = [
            phi
            for node_id in self._cluster_state.nodes()
            if node_id != self.self_node_id
            and (phi := self._failure_detector.phi(node_id, ts=now))
            is not None
        ]
        summary = {
            "live": len(self._failure_detector.live_nodes()),
            "dead": len(self._failure_detector.dead_nodes()),
            "epoch": self._cluster_state.digest_epoch,
            "max_phi": round(max(phis), 3) if phis else None,
            # Peers dead on their own announcement (graceful Leave),
            # with the announced reason — dead-with-reason, not
            # phi-inferred (docs/robustness.md).
            "departed": sorted(
                f"{nid.name}:{reason}"
                for nid, (reason, _hb) in self._departed.items()
            ),
        }
        if self._health is not None:
            summary.update(self._health.summary())
        else:
            summary["breaker_open_peers"] = []
        return summary

    def _persist_posture(self) -> str:
        """Durability/rejoin state for the telemetry digest: ``none``
        (no store), ``fresh`` (store, first boot), ``rejoin_clean`` or
        ``rejoin_unclean`` (docs/robustness.md)."""
        if self._persist is None:
            return "none"
        if self._recovered is None:
            return "fresh"
        return "rejoin_clean" if self._recovered.clean else "rejoin_unclean"

    def _publish_telemetry(self) -> None:
        """Fold a compact digest of this node's health into its OWN
        keyspace under ``TELEMETRY_KEY`` (obs/fleet.py;
        docs/observability.md "Fleet telemetry" has the key schema).
        One plain owner write per telemetry interval: it replicates
        under the existing owner-write invariant, byzantine guards,
        segments fastpath and MTU budget, and bumps the content epoch
        at most once per interval."""
        summary = self.health_summary()
        fields = {
            # Short keys (docs/observability.md): the digest rides
            # every delta to every peer, so it pays MTU per byte.
            "hb": self.self_node_state().heartbeat,
            "live": summary["live"],
            "dead": summary["dead"],
            "ep": summary["epoch"],
            "int": round(self.effective_gossip_interval, 6),
            "kv": self._engine.kv_applied_total,
            "brk": summary["breaker_open_peers"],
            "st": self._persist_posture(),
        }
        if summary.get("max_phi") is not None:
            fields["phi"] = summary["max_phi"]
        lat = round_latency_percentiles(self._round_durations or ())
        if lat is not None:
            fields["p50"] = round(lat[0], 6)
            fields["p99"] = round(lat[1], 6)
        self.set(TELEMETRY_KEY, encode_health_digest(fields))
        self._fleet_publishes.inc()

    def fleet_view(self, *, stale_s: float | None = None) -> dict:
        """Any-member fleet table assembled from the replicated
        self-telemetry (obs/fleet.py): one entry per known node with
        its decoded health digest and per-entry STALENESS — the lag
        between the digest's advertised heartbeat and this member's
        local watermark for that owner, the per-member epoch vector
        ROADMAP item 2a asks for. Entries advertising a heartbeat the
        local failure detector never credited are marked ``suspect``
        rather than trusted. ``stale_s`` filters to entries fresher
        than that many seconds. Works with telemetry publishing off
        (entries simply have no digest) — assembly reads only local
        replicated state and never blocks."""
        live = set(self._failure_detector.live_nodes())
        live.add(self.self_node_id)
        entries = []
        for node_id, ns in self.node_states_view().items():
            vv = ns.get(TELEMETRY_KEY)
            entries.append(
                build_fleet_entry(
                    node_id.name,
                    live=node_id in live,
                    heartbeat=ns.heartbeat,
                    raw=vv.value if vv is not None else None,
                )
            )
        view = assemble_fleet_view(
            entries,
            self_name=self.self_node_id.name,
            epoch=self.state_epoch(),
            stale_s=stale_s,
        )
        self._fleet_view_nodes.set(view["known"])
        if view["suspect"]:
            self._fleet_suspects.inc(view["suspect"])
        return view

    def metrics_registry(self) -> MetricsRegistry:
        """The registry this cluster reports through (the process default
        unless one was injected) — hand it to ``obs.render_prometheus`` or
        an ``obs.MetricsHTTPServer``."""
        return self._metrics

    def trace_rounds(self, trace: TraceWriter) -> None:
        """Attach a twin-grade round tracer (docs/twin.md).

        Emits one ``twin_node`` record describing this node's tuning
        surface, then one ``twin_round`` record per initiated gossip
        round carrying what the digital twin's replay needs to lift the
        trace into a simulation: the node's own round index and wall
        duration, the reconciliation volume (key-versions sent/applied
        since the previous round — responder-side handshakes included,
        that traffic is part of the round's anti-entropy work), the
        membership view (live/dead counts), our heartbeat, and the
        round's worst phi sample. Fleet traces share ONE TraceWriter
        across nodes (it is lock-serialized); replay groups by ``node``.
        Without this call nothing twin-related is emitted — the plain
        ``trace=`` constructor argument keeps its original event set.
        """
        self._twin_trace = trace
        self._twin_prev_sent = self._engine.kv_sent_total
        self._twin_prev_applied = self._engine.kv_applied_total
        trace.emit(
            "twin_node",
            node=self._config.node_id.name,
            generation=self._config.node_id.generation_id,
            gossip_interval_s=self.effective_gossip_interval,
            gossip_count=self._config.gossip_count,
            phi_threshold=self._config.failure_detector.phi_threshhold,
            max_payload_size=self._config.max_payload_size,
            n_own_keys=len(self.self_node_state().key_values),
        )

    def trace_provenance(self, trace: TraceWriter | None) -> None:
        """Attach a propagation-provenance tracer (obs/prov.py,
        docs/observability.md "Propagation & provenance").

        While attached, every owner write emits ``prov_write``, every
        guarded apply emits one ``prov_apply`` per key-version (with
        ``from_peer`` named where this receiver knows it), and every
        Ack-direction delta emits ``prov_send`` records so the
        collector can join responder-side applies to their sender.
        Fleet traces share ONE TraceWriter (lock-serialized);
        ``obs.prov.join_propagation`` builds the spread trees. None
        detaches. Without this call nothing provenance-related is
        emitted and the hot paths are byte-identical."""
        self._prov = trace
        self._engine.attach_provenance(trace)

    def flight_record(self) -> list[dict]:
        """Dump the always-on flight recorder (obs/flightrec.py): the
        last few hundred notable events this node lived through, oldest
        first — also served by the serve tier at ``/debug/flightrec``."""
        return self._flightrec.dump()

    def _note_breaker_transition(self, addr: Address, to: str) -> None:
        self._flightrec.note("breaker", peer=f"{addr[0]}:{addr[1]}", to=to)

    @property
    def fault_controller(self):
        """The FaultController compiled from ``Config.fault_plan``
        (None when no plan is set). The ChaosHarness uses this to
        synchronise one plan epoch across a fleet."""
        return self._fault_controller

    def _peer_label(self, host: str, port: int) -> str:
        """Fault-plan addressing: the peer's node *name* when the
        cluster state knows the address, else ``host:port`` (plans can
        match either — NodeSet.names accepts both forms)."""
        for node_id in self._cluster_state.nodes():
            if node_id.gossip_advertise_addr == (host, port):
                return node_id.name
        return f"{host}:{port}"

    # -- hooks ----------------------------------------------------------------

    def on_node_join(self, callback: NodeEventCallback) -> None:
        self._on_node_join.append(callback)

    def on_node_leave(self, callback: NodeEventCallback) -> None:
        self._on_node_leave.append(callback)

    def on_key_change(self, callback: KeyChangeCallback) -> None:
        self._on_key_change.append(callback)

    # Removal mirrors registration so embedders with their own lifecycle
    # (the serve tier's ServeApp, tests) can detach without leaking the
    # callback — and whatever it closes over — for the cluster's
    # lifetime. Removing a callback that is not registered is a no-op.

    def remove_on_node_join(self, callback: NodeEventCallback) -> None:
        with suppress(ValueError):
            self._on_node_join.remove(callback)

    def remove_on_node_leave(self, callback: NodeEventCallback) -> None:
        with suppress(ValueError):
            self._on_node_leave.remove(callback)

    def remove_on_key_change(self, callback: KeyChangeCallback) -> None:
        with suppress(ValueError):
            self._on_key_change.remove(callback)

    def _emit_key_change(
        self,
        node_id: NodeId,
        key: str,
        old_vv: VersionedValue | None,
        new_vv: VersionedValue,
    ) -> None:
        self._hooks.emit(tuple(self._on_key_change), (node_id, key, old_vv, new_vv))

    def _maybe_emit_key_change(
        self, key: str, old_vv: VersionedValue | None, new_vv: VersionedValue | None
    ) -> None:
        if new_vv is None:
            return
        if (
            old_vv is None
            or old_vv.version != new_vv.version
            or old_vv.status != new_vv.status
            or old_vv.value != new_vv.value
        ):
            if self._persist is not None:
                # Intent log: every effective owner write (sets,
                # tombstones, TTL marks — all versioned) journals before
                # the hooks see it, so a crash between snapshots loses
                # at most an unflushed OS buffer, never an acknowledged
                # frame (runtime/persist.py).
                self._persist.record_write(key, new_vv)
            if self._prov is not None:
                # Provenance origin (obs/prov.py): the instant this
                # owner write existed — every peer's prov_apply latency
                # for (key, version) is measured from here.
                self._prov.emit(
                    "prov_write",
                    node=self._config.node_id.name,
                    key=key,
                    version=new_vv.version,
                    t_mono=round(self._clock.monotonic(), 6),
                )
            self._emit_key_change(self.self_node_id, key, old_vv, new_vv)

    # -- owner KV API ---------------------------------------------------------

    def get(self, key: str) -> str | None:
        vv = self.self_node_state().get(key)
        return None if vv is None else vv.value

    def get_versioned(self, key: str) -> VersionedValue | None:
        return self.self_node_state().get_versioned(key)

    def set(self, key: str, value: str) -> None:
        old = self.get_versioned(key)
        self.self_node_state().set(key, value)
        self._maybe_emit_key_change(key, old, self.get_versioned(key))

    def delete(self, key: str) -> None:
        old = self.get_versioned(key)
        self.self_node_state().delete(key)
        self._maybe_emit_key_change(key, old, self.get_versioned(key))

    def set_with_ttl(self, key: str, value: str) -> None:
        old = self.get_versioned(key)
        self.self_node_state().set_with_ttl(key, value)
        self._maybe_emit_key_change(key, old, self.get_versioned(key))

    def delete_after_ttl(self, key: str) -> None:
        old = self.get_versioned(key)
        self.self_node_state().delete_after_ttl(key)
        self._maybe_emit_key_change(key, old, self.get_versioned(key))

    # -- gossip round (initiator) --------------------------------------------

    async def _gossip_round(self) -> None:
        round_start = self._clock.monotonic()
        tls_names: dict[Address, str | None] = {
            n.gossip_advertise_addr: n.tls_name
            for n in self._cluster_state.nodes()
            if n != self.self_node_id
        }
        live = {n.gossip_advertise_addr for n in self._failure_detector.live_nodes()}
        dead = {n.gossip_advertise_addr for n in self._failure_detector.dead_nodes()}
        peers = {
            n.gossip_advertise_addr
            for n in self._cluster_state.nodes()
            if n != self.self_node_id
        }
        seeds = set(self._config.seed_nodes)

        het = self._config.heterogeneity
        zone_of = None
        self_zone = None
        if het is not None and het.zone_bias > 0:
            # Zone-aware bias: addresses we can attribute to a known
            # node get that node's zone (same stable name coordinate
            # the sim buckets by); unresolved bootstrap addresses stay
            # unbiased. Zones are cached per address — only members not
            # seen before pay the name hash.
            zone_of = self._zone_cache
            for n in self._cluster_state.nodes():
                addr = n.gossip_advertise_addr
                if addr not in zone_of:
                    zone_of[addr] = het.zone_of_name(n.name)
            self_zone = self._self_zone
        # Circuit-breaker quarantine (docs/robustness.md): peers inside
        # an open backoff window are removed from every pick so a
        # broken peer stops burning a sub-exchange per round; an
        # expired window drops the peer from this set, and the next
        # draw that lands on it is the half-open probe. None (breaker
        # off, or nothing open) keeps the selection path — and its rng
        # draw sequence — byte-identical to the reference's.
        quarantined = (
            self._health.quarantined_peers()
            if self._health is not None
            else None
        )
        if quarantined and not live:
            # An isolated node (bootstrap against a still-booting seed,
            # or a fully-partitioned fleet) has no live peer to spend
            # the saved sub-exchange on — quarantine would only delay
            # the join by the accrued backoff (up to 64 intervals)
            # after the seed finally comes up. With nothing useful to
            # protect, retry at the reference cadence.
            quarantined = None
        targets, dead_target, seed_target = select_gossip_targets(
            peers, live, dead, seeds, rng=self._rng,
            gossip_count=self._config.gossip_count,
            zone_bias=0.0 if het is None else het.zone_bias,
            self_zone=self_zone,
            zone_of=zone_of,
            quarantined=quarantined or None,
        )
        if targets:
            self._peer_selection.labels("live").inc(len(targets))
        if dead_target is not None:
            self._peer_selection.labels("dead").inc()
        if seed_target is not None:
            self._peer_selection.labels("seed").inc()

        self.self_node_state().inc_heartbeat()
        if self._round_durations is not None:
            # Self-telemetry publish (obs/fleet.py): due this round, and
            # BEFORE the handshakes so the fresh digest rides this
            # round's deltas. One owner write per telemetry interval.
            self._rounds_since_telemetry += 1
            if self._rounds_since_telemetry >= self._telemetry_every_rounds:
                self._rounds_since_telemetry = 0
                self._publish_telemetry()
        self._cluster_state.gc_marked_for_deletion(
            timedelta(seconds=self._config.marked_for_deletion_grace_period)
        )
        await self._pool.evict_idle()
        if self._persist is not None and self._persist.snapshot_due():
            await self._write_persist_snapshot()

        # gather, not TaskGroup (3.11+): _gossip_with contains its own
        # failures, so plain fan-out-and-wait has identical semantics.
        handshakes = [
            self._gossip_with(host, port, "live", tls_names.get((host, port)))
            for host, port in targets
        ]
        if dead_target is not None:
            host, port = dead_target
            handshakes.append(
                self._gossip_with(host, port, "dead", tls_names.get(dead_target))
            )
        if seed_target is not None:
            host, port = seed_target
            handshakes.append(
                self._gossip_with(host, port, "seed", tls_names.get(seed_target))
            )
        if handshakes:
            await asyncio.gather(*handshakes)

        self._update_liveness()
        duration = self._clock.monotonic() - round_start
        self._round_seconds.observe(duration)
        if self._round_durations is not None:
            # Telemetry's round-latency window (p50/p99 ride the next
            # published digest).
            self._round_durations.append(duration)
        if self._trace is not None:
            self._trace.emit(
                "gossip_round",
                node=self._config.node_id.name,
                duration_s=round(duration, 6),
                targets=len(targets)
                + (dead_target is not None)
                + (seed_target is not None),
                live=len(live),
                dead=len(dead),
            )
        if self._twin_trace is not None:
            # Twin-grade round record (docs/twin.md): per-round DELTAS of
            # the engine's cumulative reconciliation totals, so replay
            # sees the anti-entropy volume each round actually moved
            # (responder-side handshakes since the last round included).
            kv_sent = self._engine.kv_sent_total
            kv_applied = self._engine.kv_applied_total
            self._twin_trace.emit(
                "twin_round",
                node=self._config.node_id.name,
                round=self._twin_round,
                duration_s=round(duration, 6),
                targets=len(targets)
                + (dead_target is not None)
                + (seed_target is not None),
                live=len(live),
                dead=len(dead),
                kv_sent=kv_sent - self._twin_prev_sent,
                kv_applied=kv_applied - self._twin_prev_applied,
                heartbeat=self.self_node_state().heartbeat,
                phi_max=round(self._last_phi_max, 4),
            )
            self._twin_round += 1
            self._twin_prev_sent = kv_sent
            self._twin_prev_applied = kv_applied

    async def _gossip_with(
        self, host: str, port: int, label: str, tls_name: str | None = None
    ) -> None:
        """One initiated handshake over a pooled connection.

        A reused connection may have been closed by the peer since its
        last handshake (close-per-handshake peers — the reference — do
        this every time; idle timeouts race borrows): that surfaces as
        EOF/reset on first use and is retried exactly once on a fresh
        dial. A fresh connection failing the same way is a real peer
        problem and is not retried.

        Overload layer (docs/robustness.md): with adaptive timeouts on,
        every wait below runs under the peer's ``mean + k*stddev``
        budget instead of the fixed constants (None until the first RTT
        sample); the measured Syn→SynAck round trip feeds the estimator
        on success, and failures feed the peer's circuit breaker. With
        both flags off ``self._health`` is None and this body is the
        reference path unchanged.
        """
        addr = (host, port)
        health = self._health
        budget = health.timeout_for(addr) if health is not None else None
        # Provenance peer name: resolved ONLY while a prov trace is
        # attached (the resolver scans known nodes — the default path
        # must not pay it per handshake).
        prov_peer = (
            self._peer_label(host, port) if self._prov is not None else None
        )
        # Wire-level span context: one handshake id per initiated
        # exchange; the encoded field is APPENDED after the cached
        # Syn/Ack parts (proto3 field order is insignificant on decode)
        # so the per-digest-epoch caches stay per-handshake-free. Off:
        # tc_field is None and every frame below is byte-identical.
        tc_field = None
        hsid: int | None = None
        tc_note: dict = {}
        if self._trace_context:
            self._next_handshake_id += 1
            hsid = self._next_handshake_id
            tc_field = encode_trace_context(
                TraceContext(self._config.node_id.name, hsid)
            )
            tc_note = {"hsid": hsid}
        flightrec = self._flightrec
        if health is not None:
            # An open breaker whose backoff just expired: this
            # handshake IS the half-open probe.
            health.begin_attempt(addr)
        async with self._gossip_semaphore:
            for attempt in (0, 1):
                conn: PooledConnection | None = None
                reused = False
                try:
                    syn_parts = (
                        self._engine.make_syn_parts()
                        if self._wire_fastpath
                        else None
                    )
                    syn_bytes = (
                        None
                        if syn_parts is not None
                        else self._engine.make_syn_bytes()
                    )
                    if tc_field is not None:
                        # Copy, never mutate: the parts list is owned by
                        # the engine's per-epoch cache.
                        if syn_parts is not None:
                            syn_parts = [*syn_parts, tc_field]
                        else:
                            syn_bytes = syn_bytes + tc_field
                    # The retry (attempt 1) must actually redial: another
                    # idle sibling of the connection that just died would
                    # burn the retry on the same peer restart.
                    conn = await self._pool.acquire(
                        host, port, tls_name, fresh=attempt > 0,
                        connect_timeout=budget,
                    )
                    reused = conn.reused
                    rtt_start = self._clock.monotonic()
                    if syn_parts is not None:
                        await self._transport.write_framed_parts(
                            conn.writer, syn_parts, "syn", timeout=budget
                        )
                    else:
                        await self._transport.write_framed(
                            conn.writer, syn_bytes, "syn", timeout=budget
                        )
                    reply = await self._transport.read_packet(
                        conn.reader, timeout=budget
                    )
                    if health is not None:
                        # The Syn→SynAck round trip is the RTT sample
                        # (Karn's rule holds: timed-out reads never
                        # reach this line).
                        health.record_rtt(
                            addr, self._clock.monotonic() - rtt_start
                        )
                    if isinstance(reply.msg, BadCluster):
                        self._log.warning(
                            f"Peer {host}:{port} rejected us: wrong cluster "
                            f"(ours={self._config.cluster_id!r})"
                        )
                        flightrec.note(
                            "handshake", peer=f"{host}:{port}", label=label,
                            outcome="bad_cluster", **tc_note,
                        )
                        if health is not None:
                            # A policy rejection over a healthy link
                            # closes the breaker — quarantine is for
                            # peers that cost time, not ones that say no.
                            health.record_success(addr)
                    elif isinstance(reply.msg, SynAck):
                        if self._wire_fastpath:
                            ack_parts = self._engine.handle_synack_parts(
                                reply, peer=prov_peer, hsid=hsid
                            )
                            if tc_field is not None:
                                # Copy — the empty-ack parts list is a
                                # cached constant.
                                ack_parts = [*ack_parts, tc_field]
                            await self._transport.write_framed_parts(
                                conn.writer, ack_parts, "ack", timeout=budget
                            )
                        else:
                            ack = self._engine.handle_synack(
                                reply, peer=prov_peer, hsid=hsid
                            )
                            if hsid is not None:
                                ack.trace = TraceContext(
                                    self._config.node_id.name, hsid
                                )
                            await self._transport.write_packet(
                                conn.writer, ack, timeout=budget
                            )
                        if self._config.persistent_connections:
                            # Settled: the finally below must not discard.
                            await self._pool.release(conn)
                            conn = None
                        # else: reference lifecycle — teardown per round,
                        # via the finally's discard.
                        flightrec.note(
                            "handshake", peer=f"{host}:{port}", label=label,
                            outcome="ok", reused=reused, **tc_note,
                        )
                        if health is not None:
                            health.record_success(addr)
                    else:
                        self._log.debug(
                            f"Unexpected gossip reply from {label} {host}:{port}"
                        )
                        flightrec.note(
                            "handshake", peer=f"{host}:{port}", label=label,
                            outcome="unexpected_reply", **tc_note,
                        )
                        if health is not None:
                            # The peer answered promptly over a healthy
                            # link (same rationale as BadCluster): the
                            # breaker must settle — a half-open probe
                            # left unreported would quarantine the peer
                            # until its probe window lapsed.
                            health.record_success(addr)
                    return
                except _PEER_CLOSED_ERRORS as exc:
                    if reused and attempt == 0:
                        # The pooled connection died between handshakes;
                        # normal against close-per-handshake peers.
                        self._pool.note_reconnect()
                        continue
                    if health is not None:
                        health.record_failure(addr)
                    flightrec.note(
                        "handshake", peer=f"{host}:{port}", label=label,
                        outcome="peer_closed", error=type(exc).__name__,
                        **tc_note,
                    )
                    self._log.debug(
                        f"Gossip with {label} {host}:{port} failed: {exc}"
                    )
                    return
                except (TimeoutError, asyncio.TimeoutError, OSError,
                        ValueError) as exc:
                    if health is not None:
                        health.record_failure(addr)
                    flightrec.note(
                        "handshake", peer=f"{host}:{port}", label=label,
                        outcome="failed", error=type(exc).__name__,
                        **tc_note,
                    )
                    self._log.debug(
                        f"Gossip with {label} {host}:{port} failed: {exc}"
                    )
                    return
                except Exception as exc:
                    flightrec.note(
                        "handshake", peer=f"{host}:{port}", label=label,
                        outcome="error", error=type(exc).__name__,
                        **tc_note,
                    )
                    self._log.exception(
                        f"Gossip with {label} {host}:{port} errored: {exc}"
                    )
                    return
                finally:
                    # Everything except a released connection — handshake
                    # failures, BadCluster, per-round lifecycle, and
                    # cancellation mid-handshake — closes here.
                    if conn is not None:
                        await self._pool.discard(conn)

    # -- responder side -------------------------------------------------------

    async def _handle_connection(
        self, reader: StreamReader, writer: StreamWriter
    ) -> None:
        """Serve Syn→SynAck→Ack handshakes on one inbound connection.

        Persistent-channel peers send many handshakes back to back; the
        loop waits up to the pool idle window for each next Syn.
        Close-per-handshake peers (the reference) disconnect after the
        Ack — EOF or a reset between handshakes is a normal close, not
        an error. The first Syn gets only the ordinary read timeout: a
        fresh connection that sends nothing is not worth holding.
        """
        handshakes = 0
        self._inbound.add(writer)
        try:
            while True:
                syn_wait = (
                    self._config.pool_idle_timeout
                    if handshakes and self._config.persistent_connections
                    else None
                )
                try:
                    packet = await self._transport.read_packet(
                        reader, timeout=syn_wait
                    )
                except asyncio.IncompleteReadError as exc:
                    if not exc.partial:
                        return  # clean EOF between handshakes
                    raise
                except (TimeoutError, asyncio.TimeoutError):
                    if handshakes:
                        return  # idle persistent channel: close quietly
                    raise
                except ConnectionResetError:
                    if handshakes:
                        return  # peer tore the channel down mid-idle
                    raise
                # Inbound traffic counts as activity for our own heartbeat.
                self.self_node_state().inc_heartbeat()
                if isinstance(packet.msg, Leave):
                    # Graceful departure: apply the final flush, move
                    # the node to dead-with-reason NOW (docs/
                    # robustness.md). Fire-and-forget — no reply.
                    if packet.cluster_id == self._config.cluster_id:
                        self._handle_leave_announcement(packet)
                    return
                if not isinstance(packet.msg, Syn):
                    self._log.debug("Unexpected first gossip message type")
                    return
                if not self._verify_peer_tls_name(packet, writer):
                    self._log.warning("TLS peer identity verification failed")
                    return
                # Echoed span context: with trace_context on AND the
                # initiator's Syn carrying one (a peer that speaks the
                # field), the SynAck names us + echoes the initiator's
                # handshake id. A context-less peer gets reference
                # frames back, byte-identical.
                reply_tc = None
                if self._trace_context and packet.trace is not None:
                    reply_tc = encode_trace_context(
                        TraceContext(
                            self._config.node_id.name,
                            packet.trace.handshake_id,
                        )
                    )
                if self._wire_fastpath:
                    resp = self._engine.handle_syn_parts(packet)
                    if isinstance(resp, Packet):  # BadCluster
                        await self._transport.write_packet(writer, resp)
                        return
                    if reply_tc is not None:
                        resp = [*resp, reply_tc]
                    await self._transport.write_framed_parts(
                        writer, resp, "synack"
                    )
                else:
                    reply = self._engine.handle_syn(packet)
                    if reply_tc is not None and not isinstance(
                        reply.msg, BadCluster
                    ):
                        reply.trace = TraceContext(
                            self._config.node_id.name,
                            packet.trace.handshake_id,
                        )
                    await self._transport.write_packet(writer, reply)
                    if isinstance(reply.msg, BadCluster):
                        return
                ack = await self._transport.read_packet(reader)
                if not isinstance(ack.msg, Ack):
                    self._log.debug("Unexpected gossip ack message type")
                    return
                # The Ack's span context names its sender exactly — the
                # blind spot the send-join heuristic existed for
                # (obs/prov.py). A context-less Ack keeps the legacy
                # null-from_peer path.
                atc = ack.trace
                self._engine.handle_ack(
                    ack,
                    from_peer=(atc.node or None) if atc is not None else None,
                    hsid=atc.handshake_id if atc is not None else None,
                )
                handshakes += 1
                if not self._config.persistent_connections:
                    return  # reference lifecycle: one handshake per conn
        except (TimeoutError, asyncio.TimeoutError, OSError,
                asyncio.IncompleteReadError, ValueError) as exc:
            self._log.debug(f"Server gossip error: {exc}")
        except Exception as exc:
            self._log.exception(f"Server gossip exception: {exc}")
        finally:
            self._inbound.discard(writer)
            writer.close()
            with suppress(Exception):
                await writer.wait_closed()

    def _handle_leave_announcement(self, packet: Packet) -> None:
        """A peer told us it is draining: apply its final delta
        (guarded), mark it dead immediately with the announced reason —
        the phi window exists to infer deaths nobody announced — and
        emit the leave hook now instead of a round later."""
        msg = packet.msg
        node_id = msg.node_id
        if node_id == self.self_node_id or not node_id.name:
            return
        self._engine.handle_leave(packet)
        self._lifecycle_events.labels("leave_received").inc()
        # Hold threshold: the leaver's announced FINAL heartbeat (it
        # stopped responding before announcing, so nothing higher can
        # exist for this incarnation) — or whatever we hold if the
        # announcement predates our knowledge somehow. The claim is
        # CAPPED relative to our own knowledge (LEAVE_HB_SLACK): the
        # one Leave field the delta guards don't cover must not let a
        # forged announcement quarantine a live victim forever.
        known = 0
        ns = self._cluster_state.node_state(node_id)
        if ns is not None:
            known = ns.heartbeat
        hb = max(known, min(msg.heartbeat, known + LEAVE_HB_SLACK))
        first_receipt = node_id not in self._departed
        self._departed.setdefault(node_id, (msg.reason, hb))
        if first_receipt and not self._closing:
            # Epidemic relay: the leaver only announced to ``fanout``
            # peers; the FIRST receipt re-announces (sans delta — the
            # flush rode the original hop) to every live peer, so one
            # informed node guarantees fleet coverage in one more hop.
            # Dedup by the departed map: each node forwards ONCE, so a
            # departure costs O(N) messages per informed node exactly
            # once — a fanout-bounded relay would be cheaper but a
            # once-per-node flood can die before full coverage (no
            # retransmission rounds), leaving stragglers to the phi
            # window the announcement exists to beat. Departures are
            # rare lifecycle events; at fleet sizes where O(N²) tiny
            # packets bite, periodic re-announcement belongs in the
            # digest instead.
            fwd = Packet(
                self._config.cluster_id,
                Leave(node_id, Delta(), msg.reason, heartbeat=msg.heartbeat),
            )
            task = asyncio.create_task(self._forward_leave(fwd))
            self._leave_forwards.add(task)
            task.add_done_callback(self._leave_forwards.discard)
        if self._failure_detector.mark_dead(node_id):
            self._fd_transitions.labels("dead").inc()
            self._flightrec.note(
                "fd", peer=node_id.name, to="dead", reason=msg.reason
            )
            if self._trace is not None:
                self._trace.emit(
                    "node_transition",
                    node=self._config.node_id.name,
                    peer=node_id.name,
                    to="dead",
                    reason=msg.reason,
                )
            if node_id in self._prev_live:
                self._prev_live.discard(node_id)
                self._hooks.emit(tuple(self._on_node_leave), (node_id,))
            self._live_gauge.set(len(self._failure_detector.live_nodes()))
            self._dead_gauge.set(len(self._failure_detector.dead_nodes()))

    async def _forward_leave(self, packet: Packet) -> None:
        """One best-effort relay hop of a departure announcement to
        every known peer (excluding the departed node itself and other
        departed peers) — fired once per departure per node (see
        _handle_leave_announcement). Known, not live: a relayer whose
        phi detector has not warmed up yet still covers the fleet, and
        a failed connect to an actually-dead peer is a cheap no-op."""
        departed_id = packet.msg.node_id
        targets = [
            n.gossip_advertise_addr
            for n in self._cluster_state.nodes()
            if n != departed_id
            and n != self.self_node_id
            and n not in self._departed
        ]
        await self._announce_packet(packet, targets)

    def departed_peers(self) -> dict[NodeId, str]:
        """Peers that announced a graceful departure and have not been
        seen alive since, with the announced reason — the
        dead-with-reason surface (/healthz includes the names)."""
        return {nid: reason for nid, (reason, _hb) in self._departed.items()}

    def _verify_peer_tls_name(self, packet: Packet, writer: StreamWriter) -> bool:
        """mTLS policy (reference server.py:585-597): when serving TLS and
        the peer presented a cert, some node in its digest must claim a
        tls_name matching the cert's SAN/CN set."""
        if self._config.tls_server_context is None:
            return True
        cert_names = self._transport.peer_cert_names(writer)
        if not cert_names:
            return True
        if not isinstance(packet.msg, Syn):
            return False
        return any(
            node_id.tls_name and node_id.tls_name in cert_names
            for node_id in packet.msg.digest.node_digests
        )

    # -- liveness -------------------------------------------------------------

    def _update_liveness(self) -> None:
        # One timestamp for the whole pass; update_node_liveness returns
        # the phi each decision actually used, so the histogram samples
        # exactly the decision values with no recomputation.
        now = utc_now()
        # A departed peer (graceful Leave) stays dead on announcement
        # authority — its recent heartbeats would otherwise keep phi low
        # and resurrect it for the rest of the sampling window. Fresh
        # heartbeat EVIDENCE (the counter moved past what we held at the
        # announcement — a clean rejoin of the same incarnation, or a
        # replica of a new one) lifts the hold and phi takes over again.
        for node_id in list(self._departed):
            ns = self._cluster_state.node_state(node_id)
            if ns is not None and ns.heartbeat > self._departed[node_id][1]:
                del self._departed[node_id]
        phi_max = 0.0
        for node_id in self._cluster_state.nodes():
            if node_id != self.self_node_id and node_id not in self._departed:
                phi = self._failure_detector.update_node_liveness(
                    node_id, ts=now
                )
                if phi is not None:
                    self._phi_hist.observe(phi)
                    phi_max = max(phi_max, phi)
        # Worst suspicion this pass — the twin_round tracer's FD datum.
        self._last_phi_max = phi_max
        live = set(self._failure_detector.live_nodes())
        for node_id in live - self._prev_live:
            self._fd_transitions.labels("live").inc()
            self._flightrec.note("fd", peer=node_id.name, to="live")
            if self._trace is not None:
                self._trace.emit(
                    "node_transition",
                    node=self._config.node_id.name,
                    peer=node_id.name,
                    to="live",
                )
            self._hooks.emit(tuple(self._on_node_join), (node_id,))
        for node_id in self._prev_live - live:
            self._fd_transitions.labels("dead").inc()
            self._flightrec.note("fd", peer=node_id.name, to="dead")
            if self._trace is not None:
                self._trace.emit(
                    "node_transition",
                    node=self._config.node_id.name,
                    peer=node_id.name,
                    to="dead",
                )
            self._hooks.emit(tuple(self._on_node_leave), (node_id,))
        self._prev_live = live
        self._live_gauge.set(len(live))
        self._dead_gauge.set(len(self._failure_detector.dead_nodes()))
        for node_id in self._failure_detector.garbage_collect():
            self._cluster_state.remove_node(node_id)
            self._departed.pop(node_id, None)
            # Wire fast path: drop the heartbeat watermark + cached
            # segments so a future re-add of this NodeId starts fresh.
            self._engine.note_node_removed(node_id)
            if self._health is not None:
                # Departed for good: evict the peer's RTT/breaker state
                # and gauge series (bounded by live membership, not by
                # cumulative address churn). Dead-but-known peers keep
                # their breakers — that quarantine is the feature.
                self._health.forget(node_id.gossip_advertise_addr)
