"""Crash-safe durable node state: snapshot + intent log + clean marker.

The reference (and this runtime with ``Config.persistence=None``) is
fully amnesiac: a restarted node boots with an empty keyspace and a
bumped generation, so every reboot discards the node's own writes and
forces the whole cluster to re-replicate its state — at scale a rolling
deploy becomes a self-inflicted full-state anti-entropy storm. This
module is the durability layer behind ``Config.persistence``
(docs/robustness.md "Durability & lifecycle"):

- **Snapshot** (``snapshot.bin``): the node's OWN keyspace — versions,
  tombstones, TTL deadlines (``status_change_ts``), ``max_version``,
  ``last_gc_version``, heartbeat, generation, the last generation this
  store ever observed (the durable strictly-increasing guard), and
  optionally the replicated peer view. Written tmp + fsync +
  ``os.replace`` (atomic on POSIX), CRC-framed; a corrupt or
  wrong-format snapshot is REFUSED loudly with a counted fallback to
  the amnesiac boot — a wrong recovery is worse than no recovery.
- **Intent log** (``intent.log``): append-only CRC-framed records, one
  per owner write between snapshots. Replay is idempotent
  (``set_versioned`` semantics); a torn tail — the kill-mid-write case
  — truncates at the last valid frame, so recovery is always either
  the pre-write or the post-write state, never a third thing
  (tests/test_persist.py tortures every byte offset).
- **Clean marker** (``clean.bin``): written ONLY by a graceful close
  (``Cluster.close``/``Cluster.leave``) and removed as the first act of
  the next boot, so its presence proves the previous shutdown flushed
  everything. A clean store lets the reboot keep its previous
  generation AND heartbeat (peers see the same incarnation resume); an
  unclean store bumps the generation (seeded above every generation the
  store ever saw, immune to a regressed wall clock) but still restores
  the keyspace at its persisted versions so peers' digest floors mean
  delta catch-up, not full re-replication.

Every durable file is framed the same way: an 8-byte little-endian
``(length, crc32)`` header followed by ``length`` payload bytes — one
frame for snapshot/marker files, back-to-back frames for the log.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field

from ..core.config import PersistenceConfig
from ..core.identity import Address, NodeId, observe_generation
from ..core.kvstate import NodeState
from ..core.values import VersionedValue, VersionStatusEnum
from ..obs.registry import MetricsRegistry
from ..utils.clock import UTC, utc_now
from datetime import datetime

# Store format version: bumped on any incompatible layout change; a
# snapshot from a different format is refused (counted corrupt).
FORMAT = 1

SNAPSHOT_FILE = "snapshot.bin"
LOG_FILE = "intent.log"
# Rotated log segment covering writes up to an in-flight snapshot's
# copy point: rotated out synchronously with the state copies
# (begin_snapshot), deleted only once the covering snapshot has
# atomically landed — a crash in between replays it on top of the older
# snapshot (idempotent), so no acknowledged frame is ever orphaned.
LOG_OLD_FILE = "intent.log.old"
CLEAN_FILE = "clean.bin"

_FRAME_HEADER = struct.Struct("<II")  # (payload length, crc32)

# A frame larger than this is treated as corruption, not a record — an
# absurd length word in a torn header must not make recovery attempt a
# multi-GB read.
MAX_FRAME_BYTES = 64 << 20


def _frame(payload: bytes) -> bytes:
    return _FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def _read_frames(raw: bytes) -> tuple[list[bytes], int]:
    """Decode back-to-back frames; returns (payloads, valid_bytes).
    Stops at the first torn/corrupt frame — ``valid_bytes`` is where a
    repairing truncate should cut."""
    out: list[bytes] = []
    pos = 0
    n = len(raw)
    while pos + _FRAME_HEADER.size <= n:
        length, crc = _FRAME_HEADER.unpack_from(raw, pos)
        start = pos + _FRAME_HEADER.size
        if length > MAX_FRAME_BYTES or start + length > n:
            break  # torn tail (or absurd length): cut here
        payload = raw[start : start + length]
        if zlib.crc32(payload) != crc:
            break  # corrupt frame: nothing after it can be trusted
        out.append(payload)
        pos = start + length
    return out, pos


def _write_atomic(path: str, payload: bytes, *, fsync: bool = True) -> None:
    """The tmp + fsync + ``os.replace`` discipline (analyzer rule
    ACT028): the final path only ever names a COMPLETE file — a crash
    mid-write leaves the previous version (or nothing), never a torn
    one. The directory is fsync'd too so the rename itself is durable."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_frame(payload))
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)


def _read_framed_file(path: str) -> bytes | None:
    """The single frame of a snapshot/marker file, or None when the
    file is absent, torn, or corrupt (callers count + decide)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    frames, _ = _read_frames(raw)
    return frames[0] if frames else None


# -- (de)serialization --------------------------------------------------------


def _ts_to_str(ts: datetime) -> str:
    return ts.astimezone(UTC).isoformat()


def _ts_from_str(raw: str) -> datetime:
    ts = datetime.fromisoformat(raw)
    return ts if ts.tzinfo is not None else ts.replace(tzinfo=UTC)


def _vv_to_obj(key: str, vv: VersionedValue) -> dict:
    return {
        "k": key,
        "v": vv.value,
        "ver": vv.version,
        "st": int(vv.status),
        "ts": _ts_to_str(vv.status_change_ts),
    }


def _vv_from_obj(obj: dict) -> tuple[str, VersionedValue]:
    return obj["k"], VersionedValue(
        obj["v"],
        int(obj["ver"]),
        VersionStatusEnum(int(obj["st"])),
        _ts_from_str(obj["ts"]),
    )


def _node_id_to_obj(node_id: NodeId) -> dict:
    host, port = node_id.gossip_advertise_addr
    return {
        "name": node_id.name,
        "gen": node_id.generation_id,
        "host": host,
        "port": port,
        "tls": node_id.tls_name,
    }


def _node_id_from_obj(obj: dict) -> NodeId:
    addr: Address = (obj["host"], int(obj["port"]))
    return NodeId(obj["name"], int(obj["gen"]), addr, obj.get("tls"))


def _node_state_to_obj(ns: NodeState) -> dict:
    return {
        "node": _node_id_to_obj(ns.node),
        "heartbeat": ns.heartbeat,
        "max_version": ns.max_version,
        "last_gc_version": ns.last_gc_version,
        "kvs": [_vv_to_obj(k, vv) for k, vv in ns.key_values.items()],
    }


def _node_state_from_obj(obj: dict) -> NodeState:
    kvs = dict(_vv_from_obj(o) for o in obj["kvs"])
    return NodeState(
        _node_id_from_obj(obj["node"]),
        heartbeat=int(obj["heartbeat"]),
        key_values=kvs,
        max_version=int(obj["max_version"]),
        last_gc_version=int(obj["last_gc_version"]),
    )


@dataclass(slots=True)
class RecoveredState:
    """What ``NodeStore.load()`` hands the booting Cluster."""

    clean: bool  # previous shutdown proved graceful (marker present)
    generation: int  # generation of the incarnation that wrote the store
    heartbeat: int  # final heartbeat (clean marker beats snapshot)
    max_version: int
    last_gc_version: int
    key_values: dict[str, VersionedValue]
    last_generation_seen: int  # durable strictly-increasing guard floor
    peers: list[NodeState] = field(default_factory=list)  # hints only


class NodeStore:
    """One node's durable store (see module docstring). Synchronous by
    design — callers run the slow paths (snapshot) off-loop via
    ``asyncio.to_thread``; the per-write log append is a buffered write
    + flush, cheap enough for the owner KV API to call inline."""

    def __init__(
        self,
        cfg: PersistenceConfig,
        *,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        self.cfg = cfg
        self.path = cfg.path
        os.makedirs(self.path, exist_ok=True)
        self._log_fh = None
        self._log_bytes = 0
        self._rounds_since_snapshot = 0
        # Snapshot writes run off-loop (asyncio.to_thread) and a
        # cancelled dispatcher cannot cancel a running thread — two
        # writes CAN overlap (a periodic one orphaned by shutdown
        # cancellation racing close()'s final one). The lock serializes
        # them and the loop-side sequence (issued by begin_snapshot,
        # single-threaded on the event loop) makes the race
        # last-COPY-wins, not last-THREAD-wins: a stale orphan arriving
        # late skips its write instead of clobbering the newer state.
        self._snap_lock = threading.Lock()
        self._snap_seq = 0
        self._snap_written = 0
        self._events = None
        if metrics is not None:
            self._events = metrics.counter(
                "aiocluster_persist_events_total",
                "Durable-store activity: snapshot (atomic keyspace "
                "snapshot written), log_append (intent record "
                "journaled), log_truncated (torn tail repaired at "
                "recovery), recovered_clean / recovered_unclean "
                "(keyspace restored, by previous-shutdown verdict), "
                "recovered_fresh (no usable store; reference amnesiac "
                "boot), corrupt_fallback (snapshot refused loudly; "
                "amnesiac boot), clean_marker (graceful-shutdown "
                "marker written)",
                labels=("event",),
            )

    def _count(self, event: str) -> None:
        if self._events is not None:
            self._events.labels(event).inc()

    def _file(self, name: str) -> str:
        return os.path.join(self.path, name)

    # -- recovery -------------------------------------------------------------

    def load(self) -> RecoveredState | None:
        """Recover the persisted state, or None for an amnesiac boot
        (fresh store, or a corrupt snapshot refused loudly). Always
        consumes the clean marker and repairs the log tail, so the
        running incarnation starts from a consistent dirty store."""
        marker_payload = _read_framed_file(self._file(CLEAN_FILE))
        # Consume the marker FIRST: from here until the next graceful
        # close, a crash must read as unclean. The removal is made
        # DURABLE (directory fsync) — an un-fsync'd unlink can
        # resurrect after power loss and make the crashed incarnation's
        # next boot falsely claim a clean shutdown.
        try:
            os.remove(self._file(CLEAN_FILE))
        except FileNotFoundError:
            pass
        else:
            dir_fd = os.open(self.path, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        snap_exists = os.path.exists(self._file(SNAPSHOT_FILE))
        snap_payload = _read_framed_file(self._file(SNAPSHOT_FILE))
        snap = None
        if snap_payload is not None:
            try:
                obj = json.loads(snap_payload)
                if obj.get("format") != FORMAT:
                    raise ValueError(f"unknown store format {obj.get('format')!r}")
                snap = obj
            except (ValueError, KeyError, TypeError):
                snap = None
        if snap is None:
            if snap_exists:
                # A snapshot file exists but cannot be trusted: refuse
                # it LOUDLY (counted) and boot amnesiac — never guess.
                # The generation guard is still seeded from whatever IS
                # readable (the marker records the last generation this
                # store issued): even a corrupt-store reboot under a
                # regressed wall clock must win newer-generation-wins.
                if marker_payload is not None:
                    try:
                        marker = json.loads(marker_payload)
                        observe_generation(
                            max(
                                int(marker.get("generation", 0)),
                                int(marker.get("last_generation_seen", 0)),
                            )
                        )
                    except (ValueError, TypeError):
                        pass
                self._count("corrupt_fallback")
            else:
                self._count("recovered_fresh")
            self._truncate_log(0)
            return None

        own = _node_state_from_obj(snap["own"])
        last_gen_seen = int(snap.get("last_generation_seen", 0))

        # Replay the intent log(s) on top of the snapshot (idempotent:
        # set_versioned skips anything at or below what we hold). A
        # rotated segment still on disk means a snapshot was in flight
        # at the crash — its frames may predate OR postdate the
        # snapshot that survived; idempotent replay covers both.
        records = self._read_rotated_log() + self._read_log()[0]
        for rec in records:
            try:
                obj = json.loads(rec)
                key, vv = _vv_from_obj(obj)
            except (ValueError, KeyError, TypeError):
                continue  # an unreadable record body: skip, keep framing
            own.set_versioned(key, vv)

        clean = False
        heartbeat = own.heartbeat
        generation = int(snap["generation"])
        if marker_payload is not None:
            try:
                marker = json.loads(marker_payload)
                if int(marker.get("generation", -1)) == generation:
                    clean = True
                    heartbeat = max(heartbeat, int(marker.get("heartbeat", 0)))
                    last_gen_seen = max(
                        last_gen_seen, int(marker.get("last_generation_seen", 0))
                    )
            except (ValueError, TypeError):
                clean = False  # unreadable marker proves nothing
        peers = []
        for obj in snap.get("peers", ()):
            try:
                peers.append(_node_state_from_obj(obj))
            except (ValueError, KeyError, TypeError):
                continue  # peers are hints; a bad one is just dropped
        recovered = RecoveredState(
            clean=clean,
            generation=generation,
            heartbeat=heartbeat,
            max_version=own.max_version,
            last_gc_version=own.last_gc_version,
            key_values=own.key_values,
            last_generation_seen=max(last_gen_seen, generation),
            peers=peers,
        )
        # Seed the process-local generation guard with everything this
        # store ever saw — the durable strictly-increasing promise.
        observe_generation(recovered.last_generation_seen)
        self._count("recovered_clean" if clean else "recovered_unclean")
        return recovered

    def _read_log(self) -> tuple[list[bytes], int]:
        try:
            with open(self._file(LOG_FILE), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return [], 0
        records, valid = _read_frames(raw)
        if valid < len(raw):
            # Torn tail (kill mid-append): truncate at the last valid
            # frame so the log is append-consistent again.
            self._truncate_log(valid)
            self._count("log_truncated")
        return records, valid

    def _read_rotated_log(self) -> list[bytes]:
        try:
            with open(self._file(LOG_OLD_FILE), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return []
        records, valid = _read_frames(raw)
        if valid < len(raw):
            self._count("log_truncated")
        return records

    def _truncate_log(self, size: int) -> None:
        try:
            with open(self._file(LOG_FILE), "ab") as f:
                f.truncate(size)
        except OSError:
            pass
        self._log_bytes = size

    # -- journaling -----------------------------------------------------------

    def record_write(self, key: str, vv: VersionedValue) -> None:
        """Append one owner write to the intent log (CRC-framed,
        flushed; fsync per ``cfg.fsync_writes``)."""
        if self._log_fh is None:
            self._log_fh = open(self._file(LOG_FILE), "ab")
            self._log_bytes = self._log_fh.tell()
        raw = _frame(
            json.dumps(_vv_to_obj(key, vv), separators=(",", ":")).encode()
        )
        self._log_fh.write(raw)
        self._log_fh.flush()
        if self.cfg.fsync_writes:
            os.fsync(self._log_fh.fileno())
        self._log_bytes += len(raw)
        self._count("log_append")

    def snapshot_due(self) -> bool:
        """One call per initiated gossip round: time for a snapshot?"""
        self._rounds_since_snapshot += 1  # noqa: ACT051 -- loop-confined counter: _snap_lock serializes off-loop snapshot FILE writes; the locked reset in begin_snapshot sits inside the rotation block incidentally, and no thread ever touches this field
        return (
            self._rounds_since_snapshot >= self.cfg.snapshot_interval_rounds
            or self._log_bytes > self.cfg.log_max_bytes
        )

    def begin_snapshot(self) -> int:
        """Start one snapshot: called SYNCHRONOUSLY with the state
        copies (on the event loop, so it is atomic with them), it
        rotates the live intent log into the covered segment and issues
        the write's sequence number. Everything journaled up to this
        instant is inside the copies about to be written; everything
        journaled after lands in the fresh live log and SURVIVES the
        snapshot — the un-synchronized truncate-after-write would have
        erased concurrent writes that the copied state predates."""
        log_path = self._file(LOG_FILE)
        old_path = self._file(LOG_OLD_FILE)
        # Same lock as the snapshot writers: an in-flight write's
        # covered-segment cleanup must not race this rotation's append
        # into the segment (the removal would take the fresh frames
        # with it). Contention is rare — the dispatcher already
        # serializes snapshots; only a shutdown-orphaned thread overlaps.
        with self._snap_lock:
            try:
                with open(log_path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                raw = b""
            if raw:
                # Append (not replace): a still-pending previous
                # rotation — its snapshot never landed — keeps its
                # frames until a snapshot that covers them is durably
                # on disk.
                with open(old_path, "ab") as f:
                    f.write(raw)
                    f.flush()
                    if self.cfg.fsync_writes:
                        os.fsync(f.fileno())
                self._truncate_log(0)
            self._rounds_since_snapshot = 0
            self._snap_seq += 1
            return self._snap_seq

    def write_snapshot(
        self,
        own: NodeState,
        generation: int,
        peers: list[NodeState] | None = None,
        seq: int | None = None,
    ) -> None:
        """Atomically persist the keyspace; the covered log segment
        (rotated out by ``begin_snapshot``) is deleted only AFTER the
        snapshot has durably landed. ``own``/``peers`` must be detached
        copies — this runs off-loop via ``asyncio.to_thread`` while
        gossip keeps mutating the live state (concurrent owner writes
        keep journaling to the fresh live log, untouched here).
        ``seq=None`` (direct synchronous callers) performs the rotation
        inline."""
        if seq is None:
            seq = self.begin_snapshot()
        payload = json.dumps(
            {
                "format": FORMAT,
                "generation": generation,
                "last_generation_seen": generation,
                "own": _node_state_to_obj(own),
                "peers": [
                    _node_state_to_obj(ns) for ns in (peers or ())
                ],
            },
            separators=(",", ":"),
        ).encode()
        with self._snap_lock:
            if seq < self._snap_written:
                # A newer snapshot (taken from newer copies) already
                # landed while this thread was orphaned mid-write
                # (shutdown cancellation cannot stop a running thread):
                # writing now would clobber newer state with older.
                return
            _write_atomic(self._file(SNAPSHOT_FILE), payload)
            self._snap_written = seq
            if seq == self._snap_seq:
                # Only the LATEST rotation's writer may drop the
                # rotated segment: a newer begin_snapshot may have
                # appended frames this snapshot's copies predate — they
                # must survive until THEIR covering snapshot lands (or
                # be replayed at recovery if it never does).
                try:
                    os.remove(self._file(LOG_OLD_FILE))
                except FileNotFoundError:
                    pass
        self._count("snapshot")

    def write_clean_marker(self, generation: int, heartbeat: int) -> None:
        """The graceful-shutdown proof: written ONLY after the final
        snapshot landed, consumed at next boot. Records the final
        heartbeat so a clean rejoin resumes the same incarnation's
        counter (peers only credit INCREASES)."""
        payload = json.dumps(
            {
                "format": FORMAT,
                "generation": generation,
                "heartbeat": heartbeat,
                "last_generation_seen": generation,
                "ts": _ts_to_str(utc_now()),
            },
            separators=(",", ":"),
        ).encode()
        _write_atomic(self._file(CLEAN_FILE), payload)
        self._count("clean_marker")

    def close(self) -> None:
        if self._log_fh is not None:
            try:
                self._log_fh.flush()
                os.fsync(self._log_fh.fileno())
            except OSError:
                pass
            self._log_fh.close()
            self._log_fh = None
