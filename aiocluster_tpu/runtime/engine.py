"""Socket-free gossip protocol engine.

The 3-way ScuttleButt handshake as pure state-machine steps over
``ClusterState`` + ``FailureDetector`` (parity: reference
server.py:327-376,599-604, which interleaves this logic with socket code).
Keeping it transport-free means the whole protocol is unit-testable by
passing packets between two engines — and it is exactly the contract the
JAX sim backend vectorises.
"""

from __future__ import annotations

from ..core.cluster_state import ClusterState
from ..core.config import Config
from ..core.failure import FailureDetector
from ..core.guards import sanitize_delta
from ..core.identity import NodeId
from ..core.kvstate import KeyChangeFn
from ..core.messages import Ack, BadCluster, Delta, Digest, Packet, Syn, SynAck
from ..obs.flightrec import FlightRecorder
from ..obs.registry import MetricsRegistry
from ..obs.trace import TraceWriter
from ..utils.clock import Clock, resolve_clock
from ..wire import encode_packet
from ..wire.segments import (
    SegmentStore,
    SharedPayloadCache,
    ack_packet_parts,
    cluster_id_field,
    syn_packet_parts,
    synack_packet_parts,
)


def _delta_kv_count(delta: Delta) -> int:
    return sum(len(nd.key_values) for nd in delta.node_deltas)


class GossipEngine:
    """Builds and consumes handshake packets for one node."""

    def __init__(
        self,
        config: Config,
        cluster_state: ClusterState,
        failure_detector: FailureDetector,
        on_key_change: KeyChangeFn | None = None,
        metrics: MetricsRegistry | None = None,
        flightrec: FlightRecorder | None = None,
        clock: Clock | None = None,
    ) -> None:
        self._config = config
        self._state = cluster_state
        self._fd = failure_detector
        self._on_key_change = on_key_change
        # Provenance t_mono stamps come from the shared clock seam so
        # they join flight-recorder/trace timestamps on one axis (and
        # compress under vtime).
        self._clock = resolve_clock(clock)
        # Post-mortem ring (obs/flightrec.py): guard rejections and
        # non-trivial applies are the engine's notable events.
        self._flightrec = flightrec
        # Propagation provenance (obs/prov.py): attached by
        # Cluster.trace_provenance, None by default — every prov branch
        # below is gated on this, so detached clusters run the exact
        # pre-provenance paths.
        self._prov: TraceWriter | None = None
        # Protocol-level telemetry: handshake steps by role/step, and the
        # reconciliation payload itself — key-version updates sent vs
        # applied (the transport counts the wire bytes; this counts the
        # anti-entropy work those bytes bought).
        self._steps = self._delta_kvs = self._digest_events = None
        self._byz_rejected = None
        if metrics is not None:
            # Byzantine defense accounting (core/guards.py): every
            # rejected violation, by kind. EXACTLY zero on honest
            # traffic (tests/test_byzantine.py pins the fault-free
            # soak), and exactly equal to the injected violation count
            # under an attack plan.
            self._byz_rejected = metrics.counter(
                "aiocluster_byzantine_rejected_total",
                "Inbound delta entries rejected by the byzantine "
                "defense guards, by violation kind",
                labels=("kind",),
            )
            self._steps = metrics.counter(
                "aiocluster_handshake_steps_total",
                "Handshake state-machine steps executed, by step",
                labels=("step",),
            )
            self._delta_kvs = metrics.counter(
                "aiocluster_delta_key_values_total",
                "Key-version updates carried by deltas, sent vs applied",
                labels=("direction",),
            )
            self._digest_events = metrics.counter(
                "aiocluster_digest_cache_events_total",
                "Incremental digest cache activity (rebuild/hit/reuse, "
                "plus encoded-Syn byte cache encode/reuse)",
                labels=("event",),
            )
        # Cached encoded Syn packet, keyed by (digest epoch, excluded
        # set): between quiescent rounds — and across the several targets
        # of one round — the identical bytes go out without re-encoding.
        self._syn_cache: tuple[int, frozenset[NodeId], bytes] | None = None
        self._digest_stats_exported: dict[str, int] = {}
        # Zero-copy wire fast path (Config.wire_fastpath, wire/
        # segments.py): the segment store (one encode per (node, key,
        # version)), the shared per-round delta payload LRU, the
        # scatter-gather Syn parts cache, and the heartbeat-observation
        # watermark cache. All None with the flag off — every step
        # below then runs the reference-shaped paths untouched.
        self._segments = None
        self._shared_payloads = None
        self._cid_field = b""
        self._syn_parts_cache: tuple[int, frozenset[NodeId], list] | None = None
        self._empty_ack_parts: list[bytes] | None = None
        self._hb_seen: dict[NodeId, int] | None = None
        self._wire_segment_events = self._wire_shared = None
        self._wire_stats_exported: dict[str, int] = {}
        if config.wire_fastpath:
            self._segments = SegmentStore()
            self._shared_payloads = SharedPayloadCache()
            self._cid_field = cluster_id_field(config.cluster_id)
            self._hb_seen = {}
            if metrics is not None:
                self._wire_segment_events = metrics.counter(
                    "aiocluster_wire_segment_events_total",
                    "Wire segment cache activity: hit (cached encode "
                    "served), miss (first encode of a (node, key, "
                    "version)), invalidate (cached entry superseded by "
                    "a newer version/status), evict (LRU bound)",
                    labels=("event",),
                )
                self._wire_shared = metrics.counter(
                    "aiocluster_wire_shared_payload_total",
                    "Shared per-round delta payload cache activity: "
                    "hit (one assembly served to another peer asking "
                    "for the same (node, floor) window), store, evict",
                    labels=("event",),
                )
        # Cumulative reconciliation totals as plain ints, kept even with
        # metrics off: the twin-grade round tracer (Cluster.trace_rounds,
        # docs/twin.md) differences them per round, and registry counters
        # are write-optimized, not cheap to read back per round.
        self.kv_sent_total = 0
        self.kv_applied_total = 0

    def _note(self, step: str, sent: Delta | None = None,
              applied: Delta | None = None,
              sent_count: int | None = None) -> None:
        # ``sent_count`` is the fast path's currency (EncodedDelta kv
        # counts — there is no Delta object to count); ``sent`` remains
        # the object path's. Either way the same totals and series move.
        if sent is not None:
            sent_count = _delta_kv_count(sent)
        if sent_count is not None:
            self.kv_sent_total += sent_count
        if applied is not None:
            self.kv_applied_total += _delta_kv_count(applied)
        if self._steps is None:
            return
        self._steps.labels(step).inc()
        if sent_count is not None:
            self._delta_kvs.labels("sent").inc(sent_count)
        if applied is not None:
            self._delta_kvs.labels("applied").inc(_delta_kv_count(applied))

    # -- digest helpers -------------------------------------------------------

    def _excluded(self) -> set[NodeId]:
        return set(self._fd.scheduled_for_deletion_nodes())

    def _self_digest(self, excluded: set[NodeId]) -> Digest:
        digest = self._state.compute_digest(excluded)
        self._sync_digest_metrics()
        return digest

    def _sync_digest_metrics(self) -> None:
        """Export ClusterState's plain digest-cache counters (core/ is
        dependency-free and can't import obs/) as registry counter deltas."""
        if self._digest_events is None:
            return
        for event, value in self._state.digest_cache_stats.items():
            prev = self._digest_stats_exported.get(event, 0)
            if value > prev:
                self._digest_events.labels(event).inc(value - prev)
                self._digest_stats_exported[event] = value

    def _sync_wire_metrics(self) -> None:
        """Export the segment/shared-payload plain counters (wire/ is
        obs-free, same rationale as the digest stats) as registry
        counter deltas."""
        if self._wire_segment_events is None or self._segments is None:
            return
        exported = self._wire_stats_exported
        for prefix, stats, counter in (
            ("seg_", self._segments.stats, self._wire_segment_events),
            ("shr_", self._shared_payloads.stats, self._wire_shared),
        ):
            for event, value in stats.items():
                k = prefix + event
                prev = exported.get(k, 0)
                if value > prev:
                    counter.labels(event).inc(value - prev)
                    exported[k] = value

    def _observe_digest(self, digest: Digest) -> None:
        """Heartbeats piggyback on digests; every one we see feeds the
        failure detector (except our own)."""
        seen = self._hb_seen
        if seen is not None:
            # Fast path: a per-peer-node watermark of the highest
            # heartbeat already processed. A population-sized digest
            # from a quiescent fleet advances one or two entries per
            # handshake; every other entry's ``apply_heartbeat`` would
            # be a guaranteed no-op (it only credits INCREASES), so the
            # state lookup is skipped wholesale. First observations
            # (watermark absent) always take the full path, which also
            # creates the node state — membership still spreads via
            # digests exactly as before. The cluster drops a node's
            # watermark when the FD garbage-collects it
            # (note_node_removed), so a re-added node re-initializes.
            me = self._config.node_id
            for node_id, nd in digest.node_digests.items():
                hb = nd.heartbeat
                prev = seen.get(node_id)
                if prev is not None and hb <= prev:
                    continue
                if node_id == me:
                    continue
                seen[node_id] = hb
                ns = self._state.node_state_or_default(node_id)
                if ns.apply_heartbeat(hb):
                    self._fd.report_heartbeat(node_id)
            return
        for node_id, nd in digest.node_digests.items():
            if node_id == self._config.node_id:
                continue
            ns = self._state.node_state_or_default(node_id)
            if ns.apply_heartbeat(nd.heartbeat):
                self._fd.report_heartbeat(node_id)

    def note_node_removed(self, node_id: NodeId) -> None:
        """Membership removal (FD garbage collection): drop the
        heartbeat watermark and the node's cached wire segments AND
        shared payloads so a future re-add observes and encodes from
        scratch — a re-added NodeState restarts its content_epoch, so
        a lingering shared payload could collide with a fresh
        (epoch, floor) key and serve a pre-removal window."""
        if self._hb_seen is not None:
            self._hb_seen.pop(node_id, None)
        if self._segments is not None:
            self._segments.invalidate_node(node_id)
            self._shared_payloads.invalidate_node(node_id)

    # -- handshake steps ------------------------------------------------------

    def make_syn(self) -> Packet:
        """Initiator step 1: advertise what we know."""
        self._note("make_syn")
        return Packet(
            self._config.cluster_id, Syn(self._self_digest(self._excluded()))
        )

    def make_syn_bytes(self) -> bytes:
        """Initiator step 1, pre-encoded: the wire bytes of ``make_syn()``'s
        packet (unframed). Cached while the digest epoch and excluded set
        are unchanged, so a quiescent node re-sends the identical bytes —
        to every target of a round, and across rounds — with zero encode
        work. The transport frames and counts them via ``write_framed``."""
        self._note("make_syn")
        excluded = self._excluded()
        key = (self._state.digest_epoch, frozenset(excluded))
        cached = self._syn_cache
        if cached is not None and (cached[0], cached[1]) == key:
            if self._digest_events is not None:
                self._digest_events.labels("syn_encode_reuse").inc()
            return cached[2]
        raw = encode_packet(
            Packet(self._config.cluster_id, Syn(self._self_digest(excluded)))
        )
        self._syn_cache = (key[0], key[1], raw)
        if self._digest_events is not None:
            self._digest_events.labels("syn_encode").inc()
        return raw

    def make_syn_parts(self) -> list[bytes]:
        """Initiator step 1, zero-copy: the Syn packet as scatter-gather
        buffers — envelope head + one memoized digest-entry buffer per
        node (``ClusterState.digest_wire_parts``). Cached whole per
        (digest epoch, excluded) like ``make_syn_bytes``; on a miss only
        the dirty entries re-encode and the envelope head (a few bytes)
        rebuilds. ``b"".join`` of the parts is byte-identical to
        ``make_syn_bytes()`` — the differential suite pins it."""
        self._note("make_syn")
        excluded = self._excluded()
        key = (self._state.digest_epoch, frozenset(excluded))
        cached = self._syn_parts_cache
        if cached is not None and (cached[0], cached[1]) == key:
            if self._digest_events is not None:
                self._digest_events.labels("syn_encode_reuse").inc()
            return cached[2]
        dparts, dtotal = self._state.digest_wire_parts(excluded)
        self._sync_digest_metrics()
        parts = syn_packet_parts(self._cid_field, dparts, dtotal)
        self._syn_parts_cache = (key[0], key[1], parts)
        if self._digest_events is not None:
            self._digest_events.labels("syn_encode").inc()
        return parts

    def handle_syn_parts(self, packet: Packet) -> Packet | list[bytes]:
        """Responder step, zero-copy: the SynAck as scatter-gather
        buffers — the per-epoch digest section plus an
        ``EncodedDelta`` packed by cached segment lengths and shared
        across peers catching up on the same windows this round.
        Returns a ``Packet`` only for the BadCluster reply (the caller
        writes that through the object path)."""
        if packet.cluster_id != self._config.cluster_id:
            self._note("bad_cluster")
            return Packet(self._config.cluster_id, BadCluster())
        assert isinstance(packet.msg, Syn)
        self._observe_digest(packet.msg.digest)
        excluded = self._excluded()
        enc = self._state.compute_partial_delta_encoded(
            packet.msg.digest,
            self._config.max_payload_size,
            excluded,
            self._segments,
            self._shared_payloads,
        )
        dparts, dtotal = self._state.digest_wire_parts(excluded)
        self._sync_digest_metrics()
        self._sync_wire_metrics()
        self._note("handle_syn", sent_count=enc.kv_count)
        return synack_packet_parts(self._cid_field, dparts, dtotal, enc)

    def handle_synack_parts(
        self, packet: Packet, peer: str | None = None,
        hsid: int | None = None,
    ) -> list[bytes]:
        """Initiator step 2, zero-copy: apply the responder's delta
        (guarded — the object was decoded from memoryview spans by the
        transport), reply with an Ack assembled from cached segments.
        An empty-delta-both-ways handshake resolves to one cached
        constant buffer list — no delta object, no encode, nothing.
        ``hsid`` is the handshake id when trace context is on — it
        rides the apply's provenance/flight-recorder records."""
        assert isinstance(packet.msg, SynAck)
        excluded = self._excluded()
        self._observe_digest(packet.msg.digest)
        applied = self._apply_guarded(
            packet.msg.delta, from_peer=peer, hsid=hsid
        )
        collect = self._prov is not None
        enc = self._state.compute_partial_delta_encoded(
            packet.msg.digest,
            self._config.max_payload_size,
            excluded,
            self._segments,
            self._shared_payloads,
            collect_kvs=collect,
        )
        if collect and enc.kv_refs:
            self._emit_prov_send_refs(enc.kv_refs, peer)
        self._note("handle_synack", sent_count=enc.kv_count, applied=applied)
        self._sync_wire_metrics()
        if enc.node_count == 0:
            parts = self._empty_ack_parts
            if parts is None:
                parts = ack_packet_parts(self._cid_field, enc)
                self._empty_ack_parts = parts
            return parts
        return ack_packet_parts(self._cid_field, enc)

    def _emit_prov_send_refs(
        self,
        kv_refs: list[tuple[str, list[tuple[str, int]]]],
        to_peer: str | None,
    ) -> None:
        """``_emit_prov_sends`` over EncodedDelta kv refs — same record
        schema, no Delta object required."""
        if to_peer is None:
            return
        t_mono = round(self._clock.monotonic(), 6)
        node = self._config.node_id.name
        for owner, refs in kv_refs:
            for key, version in refs:
                self._prov.emit(
                    "prov_send",
                    node=node,
                    to_peer=to_peer,
                    owner=owner,
                    key=key,
                    version=version,
                    t_mono=t_mono,
                )

    def handle_syn(self, packet: Packet) -> Packet:
        """Responder step: answer a Syn with our digest plus the delta the
        initiator is missing — or BadCluster on cluster-id mismatch."""
        if packet.cluster_id != self._config.cluster_id:
            self._note("bad_cluster")
            return Packet(self._config.cluster_id, BadCluster())
        assert isinstance(packet.msg, Syn)
        self._observe_digest(packet.msg.digest)
        excluded = self._excluded()
        delta = self._state.compute_partial_delta_respecting_mtu(
            packet.msg.digest, self._config.max_payload_size, excluded
        )
        self._note("handle_syn", sent=delta)
        return Packet(
            self._config.cluster_id, SynAck(self._self_digest(excluded), delta)
        )

    def attach_provenance(self, trace: TraceWriter | None) -> None:
        """Attach (or detach, with None) the propagation-provenance
        trace (obs/prov.py; wired by ``Cluster.trace_provenance``)."""
        self._prov = trace

    def _emit_prov_applies(
        self, delta: Delta, from_peer: str | None, hsid: int | None = None
    ) -> None:
        """One ``prov_apply`` per applied key-version: receiver-side
        provenance (obs/prov.py). ``from_peer`` is the peer the delta
        came from when this receiver knows it (it initiated the
        handshake, a Leave named its sender, or — with
        ``Config.trace_context`` on — the wire's span context named the
        Ack's sender); None only on legacy responder-side applies,
        which the collector joins to the initiator's ``prov_send``
        records. ``hsid`` (the wire handshake id) rides the record when
        known, correlating it with both nodes' flight recorders."""
        t_mono = round(self._clock.monotonic(), 6)
        node = self._config.node_id.name
        for nd in delta.node_deltas:
            owner = nd.node_id.name
            for kv in nd.key_values:
                if hsid is not None:
                    self._prov.emit(
                        "prov_apply",
                        node=node,
                        owner=owner,
                        key=kv.key,
                        version=kv.version,
                        from_peer=from_peer,
                        hsid=hsid,
                        t_mono=t_mono,
                    )
                else:
                    self._prov.emit(
                        "prov_apply",
                        node=node,
                        owner=owner,
                        key=kv.key,
                        version=kv.version,
                        from_peer=from_peer,
                        t_mono=t_mono,
                    )

    def _emit_prov_sends(self, delta: Delta, to_peer: str | None) -> None:
        """One ``prov_send`` per key-version packed into an Ack delta:
        the initiator knows the responder it is talking to while the
        responder cannot name its caller — these records are exactly
        what the collector joins the responder's null-``from_peer``
        applies against."""
        if to_peer is None:
            return
        t_mono = round(self._clock.monotonic(), 6)
        node = self._config.node_id.name
        for nd in delta.node_deltas:
            owner = nd.node_id.name
            for kv in nd.key_values:
                self._prov.emit(
                    "prov_send",
                    node=node,
                    to_peer=to_peer,
                    owner=owner,
                    key=kv.key,
                    version=kv.version,
                    t_mono=t_mono,
                )

    def _apply_guarded(
        self,
        delta: Delta,
        from_peer: str | None = None,
        hsid: int | None = None,
    ) -> Delta:
        """The apply-delta path: inbound deltas pass the byzantine
        defense guards (core/guards.py — owner-write, floor, over-stamp
        and max_version-support checks) before touching state. Honest
        deltas apply unchanged (the guards return the original object);
        every rejection is counted by kind. Returns what was actually
        applied. ``hsid`` — the wire-carried handshake id, when trace
        context named one — rides the flight-recorder and provenance
        records for cross-node correlation."""
        clean, rejected = sanitize_delta(delta, self._config.node_id)
        if rejected:
            if self._byz_rejected is not None:
                for kind, count in rejected.items():
                    self._byz_rejected.labels(kind).inc(count)
            if self._flightrec is not None:
                if hsid is not None:
                    self._flightrec.note(
                        "guard_reject", peer=from_peer,
                        kinds=dict(rejected), hsid=hsid,
                    )
                else:
                    self._flightrec.note(
                        "guard_reject", peer=from_peer, kinds=dict(rejected)
                    )
        self._state.apply_delta(clean, on_key_change=self._on_key_change)
        if clean.node_deltas:
            if self._flightrec is not None:
                if hsid is not None:
                    self._flightrec.note(
                        "apply",
                        peer=from_peer,
                        kvs=_delta_kv_count(clean),
                        nodes=len(clean.node_deltas),
                        hsid=hsid,
                    )
                else:
                    self._flightrec.note(
                        "apply",
                        peer=from_peer,
                        kvs=_delta_kv_count(clean),
                        nodes=len(clean.node_deltas),
                    )
            if self._prov is not None:
                self._emit_prov_applies(clean, from_peer, hsid)
        return clean

    def handle_synack(
        self, packet: Packet, peer: str | None = None,
        hsid: int | None = None,
    ) -> Packet:
        """Initiator step 2: apply the responder's delta (guarded),
        reply with the delta the responder is missing. ``peer`` names
        the responder for provenance (the initiator dialed it — the
        cluster resolves the name only while a prov trace is attached);
        ``hsid`` is the handshake id when trace context is on."""
        assert isinstance(packet.msg, SynAck)
        excluded = self._excluded()
        self._observe_digest(packet.msg.digest)
        applied = self._apply_guarded(
            packet.msg.delta, from_peer=peer, hsid=hsid
        )
        delta = self._state.compute_partial_delta_respecting_mtu(
            packet.msg.digest, self._config.max_payload_size, excluded
        )
        if self._prov is not None:
            self._emit_prov_sends(delta, peer)
        self._note("handle_synack", sent=delta, applied=applied)
        return Packet(self._config.cluster_id, Ack(delta))

    def handle_ack(
        self,
        packet: Packet,
        from_peer: str | None = None,
        hsid: int | None = None,
    ) -> None:
        """Responder final step: apply the initiator's delta (guarded).
        With ``Config.trace_context`` on the Ack's wire span context
        names its sender, so the cluster passes ``from_peer``/``hsid``
        and these applies join EXACTLY. Without it the responder cannot
        name its caller (a bare Syn carries no sender identity), the
        applies record ``from_peer=null``, and the provenance collector
        joins them to the initiator's ``prov_send`` records — the
        legacy heuristic path."""
        assert isinstance(packet.msg, Ack)
        applied = self._apply_guarded(
            packet.msg.delta, from_peer=from_peer, hsid=hsid
        )
        self._note("handle_ack", applied=applied)

    def handle_leave(self, packet: Packet) -> Delta:
        """Graceful departure (docs/robustness.md): apply the leaver's
        final flush (guarded like any delta — a forged Leave cannot
        smuggle what a forged Ack couldn't); the caller moves the node
        to dead-with-reason. Returns what was actually applied."""
        from ..core.messages import Leave

        assert isinstance(packet.msg, Leave)
        # The announcement names its sender — the one inbound message
        # whose provenance needs no send join.
        applied = self._apply_guarded(
            packet.msg.delta, from_peer=packet.msg.node_id.name
        )
        self._note("handle_leave", applied=applied)
        return applied
