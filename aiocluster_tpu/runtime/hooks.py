"""Application event hooks: node-join, node-leave, key-change.

Parity: reference server.py:50-56,177-322. Design contract: the caller's
write path never blocks on hooks. Events go through a bounded queue into a
single background worker; when the queue is full events are *dropped and
counted*, and callback exceptions are counted and logged but never
propagate. Shutdown optionally drains the queue under a timeout.
"""

from __future__ import annotations

import asyncio
import logging
from collections.abc import Awaitable, Callable
from contextlib import suppress
from dataclasses import dataclass

from ..obs.registry import MetricsRegistry

HookCallback = Callable[..., Awaitable[None]]


@dataclass(frozen=True, slots=True)
class HookStats:
    enqueued: int
    processed: int
    dropped: int
    errors: int
    queue_size: int


@dataclass(frozen=True, slots=True)
class _Event:
    callbacks: tuple[HookCallback, ...]
    args: tuple[object, ...]


class HookDispatcher:
    """Bounded-queue, single-worker async event dispatcher."""

    def __init__(
        self,
        maxsize: int,
        *,
        drain_on_shutdown: bool = True,
        shutdown_timeout: float = 5.0,
        log: logging.Logger | logging.LoggerAdapter | None = None,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        if maxsize <= 0:
            raise ValueError("hook_queue_maxsize must be > 0")
        self._queue: asyncio.Queue[_Event | None] = asyncio.Queue(maxsize=maxsize)
        self._drain_on_shutdown = drain_on_shutdown
        self._shutdown_timeout = shutdown_timeout
        self._log = log or logging.getLogger(__name__)
        self._worker: asyncio.Task[None] | None = None
        self._enqueued = 0
        self._processed = 0
        self._dropped = 0
        self._errors = 0
        # HookStats, folded into the metrics registry: same four counters
        # by outcome label, plus a live queue-depth gauge. stats() keeps
        # returning the dataclass for existing callers.
        self._events_metric = self._queue_gauge = None
        if metrics is not None:
            self._events_metric = metrics.counter(
                "aiocluster_hook_events_total",
                "Hook events by outcome (enqueued/processed/dropped/error)",
                labels=("outcome",),
            )
            self._queue_gauge = metrics.gauge(
                "aiocluster_hook_queue_size", "Hook events waiting in queue"
            )

    def _count(self, outcome: str, amount: int = 1) -> None:
        if self._events_metric is not None:
            self._events_metric.labels(outcome).inc(amount)
        if self._queue_gauge is not None:
            self._queue_gauge.set(self._queue.qsize())

    def start(self) -> None:
        if self._worker is None:
            self._worker = asyncio.create_task(self._run())

    def emit(self, callbacks: tuple[HookCallback, ...], args: tuple[object, ...]) -> None:
        """Enqueue one event; drops (and counts) when the queue is full."""
        if not callbacks:
            return
        try:
            self._queue.put_nowait(_Event(callbacks, args))
            self._enqueued += 1
            self._count("enqueued")
        except asyncio.QueueFull:
            self._dropped += 1
            self._count("dropped")

    def stats(self) -> HookStats:
        return HookStats(
            enqueued=self._enqueued,
            processed=self._processed,
            dropped=self._dropped,
            errors=self._errors,
            queue_size=self._queue.qsize(),
        )

    async def _run(self) -> None:
        while True:
            event = await self._queue.get()
            if event is None:
                self._queue.task_done()
                return
            try:
                for callback in event.callbacks:
                    try:
                        await callback(*event.args)
                    except Exception as exc:
                        self._errors += 1
                        self._count("error")
                        self._log.exception(f"Hook callback error: {exc}")
            finally:
                self._processed += 1
                self._count("processed")
                self._queue.task_done()

    async def stop(self) -> None:
        # Complete the swap-to-local idiom: the local join was already
        # here, but the field stayed set until after the awaits below,
        # so a concurrent stop() would pass the guard and drain/join the
        # same worker twice. Swap BEFORE the first suspension instead.
        worker, self._worker = self._worker, None
        if worker is None:
            return
        if self._drain_on_shutdown:
            try:
                await asyncio.wait_for(
                    self._queue.join(), timeout=self._shutdown_timeout
                )
            except (TimeoutError, asyncio.TimeoutError):
                self._dropped += self._queue.qsize()
                self._count("dropped", self._queue.qsize())
        else:
            self._dropped += self._queue.qsize()
            self._count("dropped", self._queue.qsize())

        if not worker.done():
            if self._drain_on_shutdown:
                with suppress(asyncio.QueueFull):
                    self._queue.put_nowait(None)
                try:
                    await asyncio.wait_for(worker, timeout=self._shutdown_timeout)
                except (TimeoutError, asyncio.TimeoutError):
                    worker.cancel()
            else:
                worker.cancel()
        # Terminal join of a worker we cancelled (or sent the sentinel)
        # above; stop() owns the task's whole lifecycle, so there is no
        # outer awaiter left to starve of the cancellation.
        with suppress(asyncio.CancelledError):  # noqa: ACT013 -- joining our own cancelled worker
            await worker
