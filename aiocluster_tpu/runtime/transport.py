"""TCP gossip transport: framed packets over asyncio streams with optional
TLS, per-operation timeouts, and size validation.

Parity: reference server.py:389-405,502-521,570-583 + utils.py:9-20. Wire
format: 4-byte big-endian length + proto3 packet (see wire/), identical to
the reference so both implementations interoperate on one cluster.
"""

from __future__ import annotations

import asyncio
import ssl
from asyncio import StreamReader, StreamWriter
from collections.abc import Awaitable, Callable, Sequence

from ..core.messages import Packet
from ..obs.registry import MetricsRegistry
from ..utils.framing import HEADER_SIZE, frame, frame_header, read_frame_size
from ..wire import decode_packet, encode_packet


class GossipTransport:
    """Connection plumbing shared by the initiator and responder roles."""

    def __init__(
        self,
        *,
        max_payload_size: int,
        connect_timeout: float,
        read_timeout: float,
        write_timeout: float,
        tls_server_context: ssl.SSLContext | None = None,
        tls_client_context: ssl.SSLContext | None = None,
        tls_server_hostname: str | None = None,
        metrics: MetricsRegistry | None = None,
        wire_fastpath: bool = False,
    ) -> None:
        self._max_payload_size = max_payload_size
        # Zero-copy data plane (Config.wire_fastpath): inbound frames
        # decode from memoryview spans, buffered reads/flushed drains
        # skip the wait_for task churn, and the parts write path below
        # is in use. False keeps every read/write byte- and
        # object-identical to the reference-shaped paths.
        self._wire_fastpath = wire_fastpath
        # Write-path copy accounting (plain ints — the handshake bench
        # reads them; not a metric family): payload bytes that were
        # memcpy'd into a contiguous buffer during packet assembly or
        # framing. write_packet costs 2x its payload (encode
        # materialization + frame concat), write_framed 1x (the payload
        # was already encoded; frame concat remains), scatter-gather
        # parts 0 (writelines sends the refs).
        self.copy_stats = {"payload_bytes_copied": 0}
        # The read-side frame bound. A reply frames digest + delta in
        # ONE packet: the delta is packed to at most the MTU, and any
        # functioning cluster's digest + envelope fit the MTU on their
        # own (a Syn IS digest + envelope), so 2x admits every frame a
        # correct peer can produce. The reference validates the whole
        # frame against the bare MTU, which REJECTS its own MTU-full
        # SynAcks — an anti-entropy backlog over one MTU (a rebooted
        # amnesiac node's refill) then re-sends the same oversize reply
        # every round and never converges (found by restart_bench's
        # cold arm under a shrunk MTU; migration.md difference #14).
        # Wire format and send-side packing are unchanged — this is
        # only liberal acceptance; the bound still caps per-frame
        # memory at a known multiple of the configured MTU.
        self._max_frame_size = 2 * max_payload_size
        self._connect_timeout = connect_timeout
        self._read_timeout = read_timeout
        self._write_timeout = write_timeout
        self._tls_server_context = tls_server_context
        self._tls_client_context = tls_client_context
        self._tls_server_hostname = tls_server_hostname
        # Wire-level telemetry: every framed packet counted by handshake
        # message type and direction, bytes as framed on the wire (header
        # included) — so syn (digest-only) vs synack/ack (delta-carrying)
        # traffic separates cleanly in the exposition.
        self._packets = self._bytes = None
        if metrics is not None:
            self._packets = metrics.counter(
                "aiocluster_gossip_packets_total",
                "Gossip packets by handshake message type and direction",
                labels=("type", "direction"),
            )
            self._bytes = metrics.counter(
                "aiocluster_gossip_bytes_total",
                "Framed gossip bytes on the wire (header included)",
                labels=("type", "direction"),
            )

    # -- client side ----------------------------------------------------------

    async def connect(
        self,
        host: str,
        port: int,
        tls_name: str | None = None,
        *,
        timeout: float | None = None,
    ) -> tuple[StreamReader, StreamWriter]:
        """Dial a peer. ``timeout`` overrides the configured connect
        timeout — the adaptive per-peer budget (runtime/health.py);
        None keeps the configured constant."""
        if self._tls_client_context is None:
            coro = asyncio.open_connection(host, port)
        else:
            coro = asyncio.open_connection(
                host,
                port,
                ssl=self._tls_client_context,
                server_hostname=tls_name or self._tls_server_hostname or host,
            )
        return await asyncio.wait_for(
            coro,
            timeout=self._connect_timeout if timeout is None else timeout,
        )

    # -- server side ----------------------------------------------------------

    async def start_server(
        self,
        host: str,
        port: int,
        handler: Callable[[StreamReader, StreamWriter], Awaitable[None]],
    ) -> asyncio.Server:
        return await asyncio.start_server(
            handler, host, port, ssl=self._tls_server_context
        )

    @staticmethod
    def peer_cert_names(writer: StreamWriter) -> set[str]:
        """DNS/IP SANs plus CN from the peer's TLS certificate (empty when
        the connection is plaintext or no client cert was presented)."""
        if writer.get_extra_info("ssl_object") is None:
            return set()
        cert = writer.get_extra_info("peercert") or {}
        names: set[str] = set()
        for kind, value in cert.get("subjectAltName", []):
            if kind in {"DNS", "IP Address"}:
                names.add(value)
        for rdn in cert.get("subject", []):
            for key, value in rdn:
                if key == "commonName":
                    names.add(value)
        return names

    # -- framed packet I/O ----------------------------------------------------

    async def read_packet(
        self, reader: StreamReader, timeout: float | None = None
    ) -> Packet:
        """Read one framed packet. ``timeout`` overrides the configured
        read timeout for the header wait; the payload wait takes the
        TIGHTER of the override and the configured constant — the
        server loop passes its long pool-idle window for the
        between-handshakes header wait (which must not license a
        mid-payload stall), while the client's adaptive per-peer budget
        (clamped to ``read_timeout``, runtime/health.py) must govern
        the payload too or a peer stalling after the 4-byte header
        burns the full fixed constant per round."""
        header = await self._read_exact(
            reader,
            HEADER_SIZE,
            self._read_timeout if timeout is None else timeout,
        )
        size = read_frame_size(header)
        if size <= 0 or size > self._max_frame_size:
            raise ValueError(f"invalid message size: {size}")
        raw = await self._read_exact(
            reader,
            size,
            (
                self._read_timeout
                if timeout is None
                else min(self._read_timeout, timeout)
            ),
        )
        # Fast path: decode from memoryview spans of the frame — nested
        # submessages become sub-views instead of slice copies, and only
        # leaf strings/cache keys materialize (wire/proto.py _Reader).
        packet = decode_packet(memoryview(raw) if self._wire_fastpath else raw)
        if self._packets is not None:
            kind = type(packet.msg).__name__.lower()
            self._packets.labels(kind, "in").inc()
            self._bytes.labels(kind, "in").inc(HEADER_SIZE + size)
        return packet

    async def _read_exact(
        self, reader: StreamReader, n: int, timeout: float | None
    ) -> bytes:
        """``readexactly`` under the operation's timeout budget. Fast
        path: when the bytes are ALREADY buffered (the common case
        mid-handshake — the peer's reply usually lands in one segment),
        ``readexactly`` completes synchronously and the ``wait_for``
        task it would otherwise be wrapped in is pure overhead — ~30µs
        of Task churn per wait on this container, several times per
        handshake. Nothing can block, so nothing needs a timeout; any
        actual wait takes the normal guarded path."""
        if self._wire_fastpath:
            buf = getattr(reader, "_buffer", None)
            if (
                buf is not None
                and len(buf) >= n
                and getattr(reader, "_exception", None) is None
            ):
                return await reader.readexactly(n)
        return await asyncio.wait_for(reader.readexactly(n), timeout=timeout)

    async def write_packet(
        self,
        writer: StreamWriter,
        packet: Packet,
        *,
        timeout: float | None = None,
    ) -> None:
        payload = encode_packet(packet)
        raw = frame(payload)
        self.copy_stats["payload_bytes_copied"] += 2 * len(payload)
        await self._write_raw(
            writer, raw, type(packet.msg).__name__.lower(), timeout=timeout
        )

    async def write_framed(
        self,
        writer: StreamWriter,
        payload: bytes,
        kind: str,
        *,
        timeout: float | None = None,
    ) -> None:
        """Write an already-encoded packet body (the engine's cached Syn
        bytes), framing it here. ``kind`` labels the packet metrics the
        same way ``write_packet`` derives from the message type;
        ``timeout`` overrides the configured write timeout (the
        adaptive per-peer budget)."""
        self.copy_stats["payload_bytes_copied"] += len(payload)
        await self._write_raw(writer, frame(payload), kind, timeout=timeout)

    async def write_framed_parts(
        self,
        writer: StreamWriter,
        parts: Sequence[bytes],
        kind: str,
        *,
        timeout: float | None = None,
    ) -> None:
        """Scatter-gather write of an already-encoded packet: frame
        header + every buffer via ``writelines`` — the payload is never
        concatenated (zero copy-bytes on this path; ``copy_stats``
        stays untouched).

        The assembled frame is validated against the READ-side bound
        here, at assembly time: the reader admits at most 2x the MTU
        (the PR-11 widening — see ``read_packet``), and a multi-buffer
        write has no single ``frame()`` choke point to catch an
        oversized assembly, so an over-bound frame must fail loudly at
        the sender rather than livelock as a peer-side reject-and-
        resend loop. The packer bounds the delta section to one MTU and
        a functioning cluster's digest + envelope fit another, so a
        correct assembly can never trip this."""
        total = 0
        for p in parts:
            total += len(p)
        if total > self._max_frame_size:
            raise ValueError(
                f"assembled frame of {total} bytes exceeds the "
                f"{self._max_frame_size}-byte read-side bound "
                "(2x max_payload_size) — a peer could never accept it"
            )
        if self._packets is not None:
            self._packets.labels(kind, "out").inc()
            self._bytes.labels(kind, "out").inc(HEADER_SIZE + total)
        writer.writelines([frame_header(total), *parts])
        # Drain fast path: write() already pushed everything to the
        # socket in the common case (empty transport buffer ⇒ drain
        # returns synchronously) — skip the wait_for task. Anything
        # still buffered waits under the normal timeout budget.
        transport = writer.transport
        if (
            transport is not None
            and not transport.is_closing()
            and transport.get_write_buffer_size() == 0
        ):
            await writer.drain()
            return
        await asyncio.wait_for(
            writer.drain(),
            timeout=self._write_timeout if timeout is None else timeout,
        )

    async def _write_raw(
        self,
        writer: StreamWriter,
        raw: bytes,
        kind: str,
        timeout: float | None = None,
    ) -> None:
        if self._packets is not None:
            self._packets.labels(kind, "out").inc()
            self._bytes.labels(kind, "out").inc(len(raw))
        writer.write(raw)
        await asyncio.wait_for(
            writer.drain(),
            timeout=self._write_timeout if timeout is None else timeout,
        )
