"""TCP gossip transport: framed packets over asyncio streams with optional
TLS, per-operation timeouts, and size validation.

Parity: reference server.py:389-405,502-521,570-583 + utils.py:9-20. Wire
format: 4-byte big-endian length + proto3 packet (see wire/), identical to
the reference so both implementations interoperate on one cluster.
"""

from __future__ import annotations

import asyncio
import ssl
from asyncio import StreamReader, StreamWriter
from collections.abc import Awaitable, Callable

from ..core.messages import Packet
from ..obs.registry import MetricsRegistry
from ..utils.framing import HEADER_SIZE, frame, read_frame_size
from ..wire import decode_packet, encode_packet


class GossipTransport:
    """Connection plumbing shared by the initiator and responder roles."""

    def __init__(
        self,
        *,
        max_payload_size: int,
        connect_timeout: float,
        read_timeout: float,
        write_timeout: float,
        tls_server_context: ssl.SSLContext | None = None,
        tls_client_context: ssl.SSLContext | None = None,
        tls_server_hostname: str | None = None,
        metrics: MetricsRegistry | None = None,
    ) -> None:
        self._max_payload_size = max_payload_size
        # The read-side frame bound. A reply frames digest + delta in
        # ONE packet: the delta is packed to at most the MTU, and any
        # functioning cluster's digest + envelope fit the MTU on their
        # own (a Syn IS digest + envelope), so 2x admits every frame a
        # correct peer can produce. The reference validates the whole
        # frame against the bare MTU, which REJECTS its own MTU-full
        # SynAcks — an anti-entropy backlog over one MTU (a rebooted
        # amnesiac node's refill) then re-sends the same oversize reply
        # every round and never converges (found by restart_bench's
        # cold arm under a shrunk MTU; migration.md difference #14).
        # Wire format and send-side packing are unchanged — this is
        # only liberal acceptance; the bound still caps per-frame
        # memory at a known multiple of the configured MTU.
        self._max_frame_size = 2 * max_payload_size
        self._connect_timeout = connect_timeout
        self._read_timeout = read_timeout
        self._write_timeout = write_timeout
        self._tls_server_context = tls_server_context
        self._tls_client_context = tls_client_context
        self._tls_server_hostname = tls_server_hostname
        # Wire-level telemetry: every framed packet counted by handshake
        # message type and direction, bytes as framed on the wire (header
        # included) — so syn (digest-only) vs synack/ack (delta-carrying)
        # traffic separates cleanly in the exposition.
        self._packets = self._bytes = None
        if metrics is not None:
            self._packets = metrics.counter(
                "aiocluster_gossip_packets_total",
                "Gossip packets by handshake message type and direction",
                labels=("type", "direction"),
            )
            self._bytes = metrics.counter(
                "aiocluster_gossip_bytes_total",
                "Framed gossip bytes on the wire (header included)",
                labels=("type", "direction"),
            )

    # -- client side ----------------------------------------------------------

    async def connect(
        self,
        host: str,
        port: int,
        tls_name: str | None = None,
        *,
        timeout: float | None = None,
    ) -> tuple[StreamReader, StreamWriter]:
        """Dial a peer. ``timeout`` overrides the configured connect
        timeout — the adaptive per-peer budget (runtime/health.py);
        None keeps the configured constant."""
        if self._tls_client_context is None:
            coro = asyncio.open_connection(host, port)
        else:
            coro = asyncio.open_connection(
                host,
                port,
                ssl=self._tls_client_context,
                server_hostname=tls_name or self._tls_server_hostname or host,
            )
        return await asyncio.wait_for(
            coro,
            timeout=self._connect_timeout if timeout is None else timeout,
        )

    # -- server side ----------------------------------------------------------

    async def start_server(
        self,
        host: str,
        port: int,
        handler: Callable[[StreamReader, StreamWriter], Awaitable[None]],
    ) -> asyncio.Server:
        return await asyncio.start_server(
            handler, host, port, ssl=self._tls_server_context
        )

    @staticmethod
    def peer_cert_names(writer: StreamWriter) -> set[str]:
        """DNS/IP SANs plus CN from the peer's TLS certificate (empty when
        the connection is plaintext or no client cert was presented)."""
        if writer.get_extra_info("ssl_object") is None:
            return set()
        cert = writer.get_extra_info("peercert") or {}
        names: set[str] = set()
        for kind, value in cert.get("subjectAltName", []):
            if kind in {"DNS", "IP Address"}:
                names.add(value)
        for rdn in cert.get("subject", []):
            for key, value in rdn:
                if key == "commonName":
                    names.add(value)
        return names

    # -- framed packet I/O ----------------------------------------------------

    async def read_packet(
        self, reader: StreamReader, timeout: float | None = None
    ) -> Packet:
        """Read one framed packet. ``timeout`` overrides the configured
        read timeout for the header wait; the payload wait takes the
        TIGHTER of the override and the configured constant — the
        server loop passes its long pool-idle window for the
        between-handshakes header wait (which must not license a
        mid-payload stall), while the client's adaptive per-peer budget
        (clamped to ``read_timeout``, runtime/health.py) must govern
        the payload too or a peer stalling after the 4-byte header
        burns the full fixed constant per round."""
        header = await asyncio.wait_for(
            reader.readexactly(HEADER_SIZE),
            timeout=self._read_timeout if timeout is None else timeout,
        )
        size = read_frame_size(header)
        if size <= 0 or size > self._max_frame_size:
            raise ValueError(f"invalid message size: {size}")
        raw = await asyncio.wait_for(
            reader.readexactly(size),
            timeout=(
                self._read_timeout
                if timeout is None
                else min(self._read_timeout, timeout)
            ),
        )
        packet = decode_packet(raw)
        if self._packets is not None:
            kind = type(packet.msg).__name__.lower()
            self._packets.labels(kind, "in").inc()
            self._bytes.labels(kind, "in").inc(HEADER_SIZE + size)
        return packet

    async def write_packet(
        self,
        writer: StreamWriter,
        packet: Packet,
        *,
        timeout: float | None = None,
    ) -> None:
        raw = frame(encode_packet(packet))
        await self._write_raw(
            writer, raw, type(packet.msg).__name__.lower(), timeout=timeout
        )

    async def write_framed(
        self,
        writer: StreamWriter,
        payload: bytes,
        kind: str,
        *,
        timeout: float | None = None,
    ) -> None:
        """Write an already-encoded packet body (the engine's cached Syn
        bytes), framing it here. ``kind`` labels the packet metrics the
        same way ``write_packet`` derives from the message type;
        ``timeout`` overrides the configured write timeout (the
        adaptive per-peer budget)."""
        await self._write_raw(writer, frame(payload), kind, timeout=timeout)

    async def _write_raw(
        self,
        writer: StreamWriter,
        raw: bytes,
        kind: str,
        timeout: float | None = None,
    ) -> None:
        if self._packets is not None:
            self._packets.labels(kind, "out").inc()
            self._bytes.labels(kind, "out").inc(len(raw))
        writer.write(raw)
        await asyncio.wait_for(
            writer.drain(),
            timeout=self._write_timeout if timeout is None else timeout,
        )
