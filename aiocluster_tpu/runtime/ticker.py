"""Drift-compensated periodic driver for the gossip round.

Parity: reference ticker.py:6-57, plus the startup jitter the reference left
as a TODO (ticker.py:27-28): with many nodes booting together, a random
initial delay desynchronises their rounds so gossip traffic spreads out.
"""

from __future__ import annotations

import asyncio
from collections.abc import Awaitable, Callable

from ..obs.registry import MetricsRegistry
from ..utils.clock import sleep as clock_sleep


def drift_compensated_timeout(
    interval: float, tick_start: float, tick_stop: float
) -> float:
    """Sleep for the remainder of the interval after the tick's own runtime."""
    return max(interval - (tick_stop - tick_start), 0.0)


class Ticker:
    """Runs ``tick`` every ``interval`` seconds on the event loop until
    stopped; tick errors go to ``on_error`` instead of killing the loop."""

    def __init__(
        self,
        tick: Callable[[], Awaitable[None]],
        interval: float,
        *,
        initial_delay: float = 0.0,
        timeout_func: Callable[[float, float, float], float] | None = None,
        on_error: Callable[[Exception], None] | None = None,
        metrics: MetricsRegistry | None = None,
        metrics_label: str = "tick",
    ) -> None:
        self._tick = tick
        self._interval = interval
        self._initial_delay = initial_delay
        self._timeout_func = timeout_func or drift_compensated_timeout
        self._on_error = on_error
        self._task: asyncio.Task[None] | None = None
        self._stopping = False
        # Per-tick telemetry, labelled so several tickers in one process
        # (or one registry) stay distinguishable. Overruns — ticks longer
        # than the interval, where drift compensation clamps to zero sleep
        # and the schedule slips — get their own counter.
        self._seconds = self._errors = self._overruns = None
        if metrics is not None:
            self._seconds = metrics.histogram(
                "aiocluster_ticker_seconds",
                "Wall-clock duration of one tick callback",
                labels=("ticker",),
            ).labels(metrics_label)
            self._errors = metrics.counter(
                "aiocluster_ticker_errors_total",
                "Tick callbacks that raised",
                labels=("ticker",),
            ).labels(metrics_label)
            self._overruns = metrics.counter(
                "aiocluster_ticker_overruns_total",
                "Ticks that ran longer than the interval",
                labels=("ticker",),
            ).labels(metrics_label)

    @property
    def closed(self) -> bool:
        return self._task is None

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        if self._initial_delay > 0:
            await clock_sleep(self._initial_delay)
        while not self._stopping:
            started = loop.time()
            try:
                await self._tick()
            except Exception as exc:
                if self._errors is not None:
                    self._errors.inc()
                if self._on_error is None:
                    raise
                self._on_error(exc)
            stopped = loop.time()
            if self._seconds is not None:
                self._seconds.observe(stopped - started)
                if stopped - started > self._interval:
                    self._overruns.inc()
            await clock_sleep(
                self._timeout_func(self._interval, started, stopped)
            )

    def start(self) -> None:
        self._stopping = False
        self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        self._stopping = True
        # Swap-to-local before the join suspends: a concurrent stop()
        # must see None at the guard, not cancel a task the first
        # stopper is still awaiting (``closed`` flips the moment the
        # stop commits, which is also when the swap makes it true).
        task, self._task = self._task, None
        if task is None:
            return
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:  # noqa: ACT013 -- absorbing the cancel we just issued
            # Terminal join of the tick task we just cancelled; stop()
            # owns its lifecycle and retains no other awaiter.
            pass
