"""Persistent per-peer connection pool for the gossip fast path.

The reference pays a full TCP connect/teardown per gossip handshake
(reference server.py:389-405); at a 64-node population that connect —
not the reconciliation work — dominates round latency. The pool keeps
completed-handshake connections keyed by ``(host, port, tls_name)`` and
hands them back on the next round:

- **borrow/return**: ``acquire`` pops the most recently used idle
  connection (LIFO keeps the hot socket hot and lets the cold ones age
  out) or dials a new one; ``release`` returns it, closing overflow
  beyond ``max_idle_per_peer``.
- **staleness**: a close-per-handshake peer (the reference) will have
  closed the pooled connection by the next borrow. Connections that
  already signal EOF/closing are evicted at borrow time; the race where
  the peer's FIN is still in flight surfaces as an EOF on first use,
  which the caller retries once on a fresh connection
  (``PooledConnection.reused`` says whether the retry is warranted).
- **idle eviction**: ``evict_idle`` (called once per gossip round)
  closes connections unused for ``idle_timeout`` seconds, matching the
  responder's own idle window so both ends agree on lifetime.
- **metrics**: ``aiocluster_pool_connections_open`` (gauge) and
  ``aiocluster_pool_events_total{event=hit|miss|reconnect|stale|
  evicted|discarded}`` (counter).

The pool never reads or writes the sockets beyond closing them — the
wire protocol stays entirely in transport/engine, so pooled and
unpooled nodes are indistinguishable on the wire.
"""

from __future__ import annotations

from asyncio import StreamReader, StreamWriter
from collections import deque
from collections.abc import Awaitable, Callable
from contextlib import suppress
from dataclasses import dataclass

from ..obs.registry import MetricsRegistry
from ..utils.clock import Clock, resolve_clock

PoolKey = tuple[str, int, str | None]
# Dial function; must additionally accept ``timeout=`` when the caller
# passes an adaptive connect budget (GossipTransport.connect does).
ConnectFn = Callable[..., Awaitable[tuple[StreamReader, StreamWriter]]]


@dataclass
class PooledConnection:
    """One borrowed or idle gossip connection."""

    key: PoolKey
    reader: StreamReader
    writer: StreamWriter
    reused: bool = False
    # Stamped by the pool from its clock at dial/release time (0.0 only
    # for hand-built connections in tests).
    last_used: float = 0.0

    def is_dead(self) -> bool:
        """Best-effort liveness: a peer's processed FIN/RST shows up as
        reader EOF or a closing transport without any I/O."""
        return self.writer.is_closing() or self.reader.at_eof()


class ConnectionPool:
    """Bounded per-peer pool of gossip connections (see module docstring)."""

    def __init__(
        self,
        connect: ConnectFn,
        *,
        max_idle_per_peer: int = 2,
        idle_timeout: float = 60.0,
        metrics: MetricsRegistry | None = None,
        on_dial: Callable[[PoolKey, float], None] | None = None,
        clock: Clock | None = None,
    ) -> None:
        self._connect = connect
        self._clock = resolve_clock(clock)
        self._max_idle_per_peer = max(0, max_idle_per_peer)
        self._idle_timeout = idle_timeout
        # Dial-latency observer (runtime/health.py): every successful
        # fresh dial reports its duration so the per-peer RTT estimator
        # is fed from the pool too, not only from completed handshakes.
        self._on_dial = on_dial
        self._idle: dict[PoolKey, deque[PooledConnection]] = {}
        self._open = 0
        self._closed = False
        self._open_gauge = self._events = None
        if metrics is not None:
            self._open_gauge = metrics.gauge(
                "aiocluster_pool_connections_open",
                "Pooled gossip connections currently open (idle + borrowed)",
            )
            self._events = metrics.counter(
                "aiocluster_pool_events_total",
                "Connection pool activity, by event",
                labels=("event",),
            )

    # -- internals ------------------------------------------------------------

    def _note(self, event: str) -> None:
        if self._events is not None:
            self._events.labels(event).inc()

    def _track_open(self, delta: int) -> None:
        self._open += delta
        if self._open_gauge is not None:
            self._open_gauge.set(self._open)

    async def _close_conn(self, conn: PooledConnection, event: str) -> None:
        self._track_open(-1)
        self._note(event)
        conn.writer.close()
        with suppress(Exception):
            await conn.writer.wait_closed()

    # -- borrow / return ------------------------------------------------------

    @property
    def open_connections(self) -> int:
        return self._open

    def idle_connections(self) -> int:
        return sum(len(q) for q in self._idle.values())

    async def acquire(
        self,
        host: str,
        port: int,
        tls_name: str | None = None,
        *,
        fresh: bool = False,
        connect_timeout: float | None = None,
    ) -> PooledConnection:
        """Borrow a connection to ``(host, port)``: the freshest live
        idle one, else a new dial. The caller owns it until ``release``
        or ``discard``. ``fresh=True`` (the EOF-retry path) flushes any
        remaining idle connections for the peer and always dials — a
        reused connection just died, so its idle siblings predate the
        same peer restart and must not consume the retry.
        ``connect_timeout`` overrides the transport's configured dial
        timeout (the adaptive per-peer budget, runtime/health.py); None
        keeps the configured constant and the exact legacy call shape."""
        key: PoolKey = (host, port, tls_name)
        queue = self._idle.get(key)
        while queue:
            if fresh:
                await self._close_conn(queue.pop(), "stale")
                continue
            conn = queue.pop()
            if conn.is_dead():
                await self._close_conn(conn, "stale")
                continue
            conn.reused = True
            self._note("hit")
            return conn
        self._note("miss")
        dial_start = self._clock.monotonic()
        if connect_timeout is None:
            reader, writer = await self._connect(host, port, tls_name)
        else:
            reader, writer = await self._connect(
                host, port, tls_name, timeout=connect_timeout
            )
        if self._on_dial is not None:
            self._on_dial(key, self._clock.monotonic() - dial_start)
        self._track_open(+1)
        return PooledConnection(
            key, reader, writer, last_used=self._clock.monotonic()
        )

    async def release(self, conn: PooledConnection) -> None:
        """Return a healthy connection to the idle pool (closing it
        instead if the pool is closed, the connection died in flight, or
        the per-peer idle bound is reached)."""
        if self._closed or conn.is_dead():
            await self._close_conn(conn, "discarded")
            return
        conn.last_used = self._clock.monotonic()
        conn.reused = False
        queue = self._idle.setdefault(conn.key, deque())
        queue.append(conn)
        while len(queue) > self._max_idle_per_peer:
            await self._close_conn(queue.popleft(), "evicted")

    async def discard(self, conn: PooledConnection) -> None:
        """Close a borrowed connection that failed mid-handshake."""
        await self._close_conn(conn, "discarded")

    def note_reconnect(self) -> None:
        """Record that a reused connection died on first use and the
        handshake is retrying on a fresh dial."""
        self._note("reconnect")

    # -- lifecycle ------------------------------------------------------------

    async def evict_idle(self, now: float | None = None) -> int:
        """Close idle connections unused for ``idle_timeout`` seconds.
        Returns how many were evicted. Cheap when nothing is idle — the
        gossip round calls this once per tick."""
        now = self._clock.monotonic() if now is None else now
        evicted = 0
        for key in list(self._idle):
            queue = self._idle[key]
            # Oldest sit at the left (LIFO reuse from the right).
            while queue and now - queue[0].last_used > self._idle_timeout:
                await self._close_conn(queue.popleft(), "evicted")
                evicted += 1
            if not queue:
                del self._idle[key]
        return evicted

    async def close(self) -> None:
        """Close every idle connection and refuse future pooling
        (borrowed connections close via their in-flight release)."""
        self._closed = True
        for queue in self._idle.values():
            while queue:
                await self._close_conn(queue.pop(), "evicted")
        self._idle.clear()
