"""Random peer selection for one gossip round.

Parity: reference server.py:656-717. Three picks per round:

- up to ``gossip_count`` targets sampled from live peers (from *all* known
  peers during cold start, when nothing is live yet);
- maybe one dead peer, with probability dead/(live+1) — so dead nodes keep
  being probed and can rejoin;
- maybe one seed, with probability seeds/(live+dead), forced when nothing
  is live — guards against network partitions healing around stale views.

All randomness flows through an injected ``random.Random`` (determinism
seam for tests, reference server.py:79,122).
"""

from __future__ import annotations

from random import Random

from ..core.identity import Address


def pick_dead_node(
    dead_nodes: set[Address],
    live_count: int,
    dead_count: int,
    rng: Random,
) -> Address | None:
    if not dead_nodes:
        return None
    if rng.random() < dead_count / (live_count + 1):
        return rng.choice(sorted(dead_nodes))
    return None


def pick_seed_node(
    seed_nodes: set[Address],
    live_count: int,
    dead_count: int,
    rng: Random,
) -> Address | None:
    if not seed_nodes:
        return None
    known = live_count + dead_count
    probability = 1.0 if known == 0 else len(seed_nodes) / known
    if live_count == 0 or rng.random() <= probability:
        return rng.choice(sorted(seed_nodes))
    return None


def _zone_biased_sample(
    pool: list[Address],
    count: int,
    rng: Random,
    zone_bias: float,
    self_zone: int | None,
    zone_of: dict[Address, int],
) -> list[Address]:
    """``count`` targets without replacement: each slot prefers the
    node's own zone with probability ``zone_bias`` (falling back to the
    whole remaining pool when no same-zone candidate is left) —
    heterogeneity's zone-aware selection (models/topology.py). The
    unbiased path never reaches here, so reference-parity sampling
    stays byte-identical."""
    remaining = list(pool)
    targets: list[Address] = []
    for _ in range(min(count, len(pool))):
        same = [a for a in remaining if zone_of.get(a) == self_zone]
        candidates = (
            same if same and rng.random() < zone_bias else remaining
        )
        pick = rng.choice(candidates)
        remaining.remove(pick)
        targets.append(pick)
    return targets


def select_gossip_targets(
    peer_nodes: set[Address],
    live_nodes: set[Address],
    dead_nodes: set[Address],
    seed_nodes: set[Address],
    rng: Random,
    gossip_count: int = 3,
    zone_bias: float = 0.0,
    self_zone: int | None = None,
    zone_of: dict[Address, int] | None = None,
    quarantined: set[Address] | None = None,
) -> tuple[list[Address], Address | None, Address | None]:
    """Returns (live targets, optional dead target, optional seed target).

    ``quarantined`` (runtime/health.py circuit breakers, docs/
    robustness.md) removes broken peers from EVERY pick — live draw,
    dead probe and seed fallback alike: a peer inside its backoff
    window must not burn a sub-exchange in any role; the half-open
    probe is the sanctioned re-contact (an expired backoff drops the
    peer from the set before this is called). None/empty leaves all
    four candidate sets — and the rng draw sequence — untouched.
    """
    if quarantined:
        peer_nodes = peer_nodes - quarantined
        live_nodes = live_nodes - quarantined
        dead_nodes = dead_nodes - quarantined
        seed_nodes = seed_nodes - quarantined
    live_count = len(live_nodes)
    dead_count = len(dead_nodes)

    pool = sorted(peer_nodes if live_count == 0 else live_nodes)
    if zone_bias > 0 and zone_of:
        targets = _zone_biased_sample(
            pool, gossip_count, rng, zone_bias, self_zone, zone_of
        )
    else:
        targets = rng.sample(pool, min(gossip_count, len(pool)))

    dead_target = pick_dead_node(dead_nodes, live_count, dead_count, rng)

    # Skip the seed pick when this round already reaches a seed, unless the
    # live set is still smaller than the seed list (bootstrap phase).
    reaches_seed = any(t in seed_nodes for t in targets)
    seed_target = None
    if not reaches_seed or live_count < len(seed_nodes):
        seed_target = pick_seed_node(seed_nodes, live_count, dead_count, rng)

    return targets, dead_target, seed_target
