"""Per-peer health economics: adaptive timeouts + circuit breaking.

The reference inherits chitchat's fixed-constant liveness posture: every
transport operation waits the same static 3 s (core/config.py), and a
peer that keeps failing is redialed at full cadence forever. Both are
wrong under load — a slow peer burns a full timeout per round per
initiator (timeout pileup is how gossip fleets collapse), and a dead
peer keeps costing a sub-exchange every round. The phi-accrual detector
already proves the fix: per-peer interarrival statistics. This module
applies the same idea to *timeouts and retry policy* (the way
Cassandra's dynamic snitch turns its phi detector into routing):

- :class:`PeerRtt` — EWMA mean + variance of measured handshake RTTs
  (TCP-RTO style: ``alpha=1/8``, ``beta=1/4``; the first sample seeds
  ``mean=rtt, stddev=rtt/2``). The adaptive timeout is
  ``mean + k*stddev`` clamped to ``[min_timeout, max_timeout]`` —
  failures on a healthy link surface in tens of milliseconds instead
  of the configured ceiling. Only successful handshakes feed the
  estimator (Karn's rule: a timed-out exchange has no RTT).
- :class:`PeerBreaker` — closed → open → half-open per peer. ``open``
  quarantines the peer from the gossip target draw for a
  decorrelated-jitter exponential backoff (``uniform(base, 3*prev)``
  capped); when it expires the next draw admits exactly one probe
  (half-open). Success closes, failure re-opens with a grown window.
- :class:`HealthTracker` — the per-cluster container the runtime wires
  in (runtime/cluster.py), keyed by peer address. Metrics:
  ``aiocluster_peer_rtt_seconds`` (histogram),
  ``aiocluster_breaker_state{peer}`` (0 closed / 1 open / 2 half-open)
  and ``aiocluster_breaker_transitions_total{to}``.

Both behaviors are feature-flagged on :class:`~..core.config.Config`
(``adaptive_timeouts``, ``circuit_breaker``, default on); with both off
the cluster constructs no tracker and every code path is byte-identical
to the reference posture (docs/robustness.md). The sim lowers the
breaker's quarantine to a per-round peer-selection mask
(faults/sim.quarantine_mask) so fleet-scale scenarios stay
differentially comparable.

All time flows through the ``utils.clock.Clock`` seam (the SAME seam
FaultController and the pool use): real monotonic by default, a
``ManualClock`` in transition tests, the loop's virtual clock under
``vtime`` — which is how breaker backoff windows compress with
everything else (docs/virtual-time.md).
"""

from __future__ import annotations

import math
from collections.abc import Callable
from random import Random

from ..obs.registry import MetricsRegistry
from ..utils.clock import Clock, resolve_clock

# Breaker states, exported as the aiocluster_breaker_state gauge value.
CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}

# EWMA gains (RFC 6298's srtt/rttvar shape, variance instead of mean
# deviation so the timeout is literally mean + k*stddev).
_ALPHA = 0.125
_BETA = 0.25

Address = tuple[str, int]


class PeerRtt:
    """EWMA mean/variance of one peer's handshake RTTs."""

    __slots__ = ("mean", "var", "samples")

    def __init__(self) -> None:
        self.mean = 0.0
        self.var = 0.0
        self.samples = 0

    def observe(self, rtt: float) -> None:
        if self.samples == 0:
            self.mean = rtt
            self.var = (rtt / 2.0) ** 2
        else:
            delta = rtt - self.mean
            self.mean += _ALPHA * delta
            self.var = (1.0 - _BETA) * self.var + _BETA * delta * delta
        self.samples += 1

    def timeout(self, k: float, lo: float, hi: float) -> float | None:
        """``mean + k*stddev`` clamped to [lo, hi]; None before the
        first sample (callers fall back to the configured constant)."""
        if self.samples == 0:
            return None
        return min(hi, max(lo, self.mean + k * math.sqrt(self.var)))


class PeerBreaker:
    """Closed → open (backoff) → half-open (single probe) for one peer."""

    __slots__ = ("state", "failures", "backoff", "open_until", "opens")

    def __init__(self) -> None:
        self.state = CLOSED
        self.failures = 0  # consecutive
        self.backoff = 0.0  # current open window, seconds
        self.open_until = 0.0
        self.opens = 0  # closed/half-open -> open transitions, lifetime

    def quarantined(self, now: float) -> bool:
        """Excluded from the gossip target draw? Open-with-expired-
        backoff is NOT quarantined — the next draw is the probe.
        Half-open quarantines only until ``open_until`` (the probe
        window stamped by ``begin_attempt``): a probe whose handshake
        dies without reporting (cancellation, an unclassified
        exception path) must not quarantine the peer forever — the
        window lapsing re-admits the next draw as a fresh probe."""
        if self.state == CLOSED:
            return False
        return now < self.open_until


class HealthTracker:
    """Per-peer RTT estimators + breakers for one cluster (see module
    docstring). ``base_backoff``/``max_backoff`` are in seconds — the
    cluster scales its configured interval counts by the effective
    gossip interval before constructing this."""

    def __init__(
        self,
        *,
        adaptive: bool = True,
        breaker: bool = True,
        k: float = 4.0,
        min_timeout: float = 0.25,
        max_timeout: float = 3.0,
        failure_threshold: int = 3,
        base_backoff: float = 2.0,
        max_backoff: float = 64.0,
        rng: Random | None = None,
        clock: Clock | None = None,
        metrics: MetricsRegistry | None = None,
        on_transition: Callable[[Address, str], None] | None = None,
    ) -> None:
        self.adaptive = adaptive
        self.breaker = breaker
        self._k = k
        self._min_timeout = min_timeout
        self._max_timeout = max_timeout
        self._threshold = max(1, failure_threshold)
        self._base_backoff = max(1e-6, base_backoff)
        self._max_backoff = max(self._base_backoff, max_backoff)
        self._rng = rng if rng is not None else Random()
        self._clock = resolve_clock(clock)
        self._rtt: dict[Address, PeerRtt] = {}
        self._breakers: dict[Address, PeerBreaker] = {}
        # Transition hook beyond metrics: the cluster's flight recorder
        # notes every breaker flip (with the peer) — sequence evidence
        # a by-new-state counter cannot carry.
        self._on_transition = on_transition
        self._rtt_hist = self._state_gauge = self._transitions = None
        if metrics is not None:
            self._rtt_hist = metrics.histogram(
                "aiocluster_peer_rtt_seconds",
                "Measured gossip handshake round-trip times, per sample",
                buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                         0.5, 1.0, 2.5, 5.0),
            )
            self._state_gauge = metrics.gauge(
                "aiocluster_breaker_state",
                "Per-peer circuit-breaker state "
                "(0 closed, 1 open, 2 half-open)",
                labels=("peer",),
            )
            self._transitions = metrics.counter(
                "aiocluster_breaker_transitions_total",
                "Circuit-breaker state transitions, by new state",
                labels=("to",),
            )

    # -- internals ------------------------------------------------------------

    def _breaker_for(self, addr: Address) -> PeerBreaker:
        b = self._breakers.get(addr)
        if b is None:
            b = self._breakers[addr] = PeerBreaker()
        return b

    def _set_state(self, addr: Address, b: PeerBreaker, state: int) -> None:
        if state == b.state:
            return
        b.state = state
        if self._state_gauge is not None:
            self._state_gauge.labels(f"{addr[0]}:{addr[1]}").set(state)
        if self._transitions is not None:
            self._transitions.labels(_STATE_NAMES[state]).inc()
        if self._on_transition is not None:
            self._on_transition(addr, _STATE_NAMES[state])

    # -- adaptive timeouts ----------------------------------------------------

    def record_rtt(self, addr: Address, rtt: float) -> None:
        """One measured successful-operation RTT (a pooled dial, a
        Syn→SynAck round trip). Feeds the estimator regardless of the
        adaptive flag — the stats are cheap and /healthz reports them —
        but only ``timeout_for`` consults the flag."""
        stats = self._rtt.get(addr)
        if stats is None:
            stats = self._rtt[addr] = PeerRtt()
        stats.observe(rtt)
        if self._rtt_hist is not None:
            self._rtt_hist.observe(rtt)

    def timeout_for(self, addr: Address) -> float | None:
        """The per-peer adaptive timeout in force, or None (use the
        configured constants: adaptive disabled, or no samples yet)."""
        if not self.adaptive:
            return None
        stats = self._rtt.get(addr)
        if stats is None:
            return None
        return stats.timeout(self._k, self._min_timeout, self._max_timeout)

    # -- circuit breaker ------------------------------------------------------

    def begin_attempt(self, addr: Address) -> None:
        """Called at handshake start: an open breaker whose backoff has
        expired transitions to half-open — THIS attempt is the probe.
        The probe holds the quarantine for one base-backoff window
        only; if its result never lands the window lapses and the next
        draw probes again (see ``PeerBreaker.quarantined``)."""
        if not self.breaker:
            return
        b = self._breakers.get(addr)
        if b is None or b.state not in (OPEN, HALF_OPEN):
            return
        if self._clock.monotonic() >= b.open_until:
            b.open_until = self._clock.monotonic() + self._base_backoff
            self._set_state(addr, b, HALF_OPEN)

    def record_success(self, addr: Address) -> None:
        if not self.breaker:
            return
        b = self._breakers.get(addr)
        if b is None:
            return
        b.failures = 0
        b.backoff = 0.0
        self._set_state(addr, b, CLOSED)

    def record_failure(self, addr: Address) -> None:
        """One failed handshake. At ``failure_threshold`` consecutive
        failures (or any half-open probe failure) the breaker opens
        with decorrelated-jitter backoff: uniform(base, 3*prev) capped
        at max — desynchronizing a fleet's retries against a struggling
        peer instead of thundering at a shared cadence."""
        if not self.breaker:
            return
        b = self._breaker_for(addr)
        b.failures += 1
        if b.state == HALF_OPEN or (
            b.state == CLOSED and b.failures >= self._threshold
        ):
            self._open(addr, b)
        elif b.state == OPEN and self._clock.monotonic() >= b.open_until:
            # A non-probe path (a dead/seed pick raced the draw) failed
            # after expiry: re-open rather than leaving a stale window.
            self._open(addr, b)

    def _open(self, addr: Address, b: PeerBreaker) -> None:
        prev = b.backoff if b.backoff > 0 else self._base_backoff
        b.backoff = min(
            self._max_backoff, self._rng.uniform(self._base_backoff, prev * 3)
        )
        b.open_until = self._clock.monotonic() + b.backoff
        b.opens += 1
        # Force the transition even from OPEN (re-open = new window).
        if b.state == OPEN:
            if self._transitions is not None:
                self._transitions.labels("open").inc()
            if self._on_transition is not None:
                self._on_transition(addr, "open")
        else:
            self._set_state(addr, b, OPEN)

    def forget(self, addr: Address) -> None:
        """Evict one peer's estimator, breaker and gauge series — the
        membership-GC hook (runtime/cluster.py): a node garbage-
        collected out of cluster state will never be drawn again, and
        without eviction the per-peer maps (and the
        ``aiocluster_breaker_state{peer}`` label set) grow forever
        under restart-with-fresh-port churn. A merely-DEAD peer is
        never forgotten: its breaker state is the point."""
        self._rtt.pop(addr, None)
        if self._breakers.pop(addr, None) is not None and (
            self._state_gauge is not None
        ):
            self._state_gauge.remove(f"{addr[0]}:{addr[1]}")

    def quarantined_peers(self) -> set[Address]:
        """Peers currently excluded from the gossip target draw (open
        inside their backoff window, or half-open probe in flight).
        Empty when the breaker is disabled."""
        if not self.breaker:
            return set()
        now = self._clock.monotonic()
        return {a for a, b in self._breakers.items() if b.quarantined(now)}

    def open_peer_labels(self) -> list[str]:
        """``host:port`` labels of peers whose breaker is not closed —
        the /healthz degraded-state field."""
        return sorted(
            f"{a[0]}:{a[1]}"
            for a, b in self._breakers.items()
            if b.state != CLOSED
        )

    # -- reporting ------------------------------------------------------------

    def breaker_state(self, addr: Address) -> int:
        b = self._breakers.get(addr)
        return CLOSED if b is None else b.state

    def breaker_opens(self, addr: Address) -> int:
        b = self._breakers.get(addr)
        return 0 if b is None else b.opens

    def timeouts_in_force(self) -> list[float]:
        """The adaptive timeouts currently in force across sampled
        peers (empty when adaptive is off) — benchmarks quantile this
        into ``adaptive_timeout_p99_ms``."""
        if not self.adaptive:
            return []
        return [
            t
            for s in self._rtt.values()
            if (t := s.timeout(self._k, self._min_timeout, self._max_timeout))
            is not None
        ]

    def summary(self) -> dict:
        """Compact degraded-state summary for /healthz."""
        timeouts = self.timeouts_in_force()
        return {
            "adaptive_timeouts": self.adaptive,
            "circuit_breaker": self.breaker,
            "peers_sampled": len(self._rtt),
            "breaker_open_peers": self.open_peer_labels(),
            "adaptive_timeout_max_ms": (
                round(max(timeouts) * 1000.0, 3) if timeouts else None
            ),
        }
