"""Asyncio socket backend: real clusters over TCP/TLS with framed proto3
packets, wire-compatible with the reference implementation."""

from .cluster import Cluster, ClusterSnapshot, KeyChangeCallback, NodeEventCallback
from .engine import GossipEngine
from .hooks import HookDispatcher, HookStats
from .peers import pick_dead_node, pick_seed_node, select_gossip_targets
from .ticker import Ticker
from .transport import GossipTransport

__all__ = (
    "Cluster",
    "ClusterSnapshot",
    "GossipEngine",
    "GossipTransport",
    "HookDispatcher",
    "HookStats",
    "KeyChangeCallback",
    "NodeEventCallback",
    "Ticker",
    "pick_dead_node",
    "pick_seed_node",
    "select_gossip_targets",
)
