"""ACT00x — style/import hygiene (migrated from the original
tools/lint.py so one engine parses each file once).

Migration note (ACT002): the old lint credited an import as "used" when
its name appeared in ANY string constant — including docstrings — so an
unused import mentioned in prose was never reported (tools/lint.py
lines 123-126 in the pre-migration version). Here string-scan credit is
restricted to *annotation contexts* (string annotations on arguments,
returns, AnnAssigns, and ``typing.cast`` targets), which is the only
place a string legitimately stands for a name.
"""

from __future__ import annotations

import ast
import re

from .core import FileContext, Finding, rule


def _module_all(tree: ast.Module) -> list[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        value = ast.literal_eval(node.value)
                    except ValueError:
                        return []
                    return [str(v) for v in value]
    return []


def _top_level_names(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                names.add((alias.asname or alias.name).split(".")[0])
    return names


@rule("ACT001", "syntax-error", "file does not parse")
def check_syntax(ctx: FileContext):
    if ctx.syntax_error is not None:
        yield Finding(
            ctx.relpath,
            ctx.syntax_error.lineno or 1,
            0,
            "ACT001",
            f"syntax error: {ctx.syntax_error.msg}",
        )


def _names_in_annotation_string(s: str) -> set[str]:
    try:
        t = ast.parse(s, mode="eval")
    except SyntaxError:
        return {tok for tok in re.split(r"\W+", s) if tok}
    return {n.id for n in ast.walk(t) if isinstance(n, ast.Name)}


def _annotation_string_names(tree: ast.Module, ctx: FileContext) -> set[str]:
    """Names inside string annotations (and typing.cast first args) —
    the ONLY strings that credit an import as used."""
    ann_nodes: list[ast.expr] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            ann_nodes.append(node.annotation)
        elif isinstance(node, ast.arg) and node.annotation is not None:
            ann_nodes.append(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.returns is not None:
                ann_nodes.append(node.returns)
        elif (
            isinstance(node, ast.Call)
            and ctx.resolve(node.func) == "typing.cast"
            and node.args
        ):
            ann_nodes.append(node.args[0])
    names: set[str] = set()
    for ann in ann_nodes:
        for c in ast.walk(ann):
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                names |= _names_in_annotation_string(c.value)
    return names


@rule("ACT002", "unused-import", "module-scope import never used")
def check_unused_imports(ctx: FileContext):
    tree = ctx.tree
    if tree is None:
        return
    if ctx.path.name == "__init__.py":
        return  # package re-export surface
    exported = set(_module_all(tree))
    imports: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = (alias.asname or alias.name).split(".")[0]
                imports.setdefault(bound, node.lineno)
    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    used |= _annotation_string_names(tree, ctx)
    for name, lineno in imports.items():
        if name not in used and name not in exported:
            yield ctx.finding(lineno, "ACT002", f"unused import '{name}'")


@rule("ACT003", "duplicate-import", "same binding imported twice")
def check_duplicate_imports(ctx: FileContext):
    tree = ctx.tree
    if tree is None:
        return
    seen_targets: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = (alias.asname or alias.name).split(".")[0]
                # Dedup on the full dotted target: `import a.b` and
                # `import a.c` both bind `a` but are not duplicates.
                target = alias.asname or alias.name
                if isinstance(node, ast.ImportFrom):
                    target = f"{node.module}:{target}"
                if target in seen_targets:
                    yield ctx.finding(
                        node.lineno, "ACT003", f"duplicate import of '{bound}'"
                    )
                else:
                    seen_targets.add(target)


@rule("ACT004", "undefined-export", "__all__ names a missing binding")
def check_all_exports(ctx: FileContext):
    tree = ctx.tree
    if tree is None:
        return
    exported = _module_all(tree)
    if not exported:
        return
    # PEP 562 lazy exports: a module __getattr__ may serve any name.
    if any(
        isinstance(n, ast.FunctionDef) and n.name == "__getattr__" for n in tree.body
    ):
        return
    defined = _top_level_names(tree)
    for name in exported:
        if name not in defined:
            yield ctx.finding(1, "ACT004", f"__all__ exports undefined name '{name}'")


@rule("ACT005", "tab-indent", "tab character in indentation")
def check_tabs(ctx: FileContext):
    for lineno, line in enumerate(ctx.lines, 1):
        indent = line[: len(line) - len(line.lstrip())]
        if "\t" in indent:
            yield ctx.finding(lineno, "ACT005", "tab in indentation")


@rule("ACT006", "trailing-whitespace", "whitespace at end of line")
def check_trailing_ws(ctx: FileContext):
    for lineno, line in enumerate(ctx.lines, 1):
        if line != line.rstrip():
            yield ctx.finding(lineno, "ACT006", "trailing whitespace")
