"""ACT02x — JAX purity / tracer discipline.

The sim backend's whole performance story is "the jit'd hot loop never
talks to the host" (PR 1's device-scalar buffering exists because the
host-sync-in-hot-loop bug class is real here). These rules catch the
three ways that discipline erodes: impure host calls inside traced
code (ACT020 — they freeze a trace-time value into the compiled
artifact), device syncs inside host loops (ACT021 — each one stalls
the dispatch pipeline), and jnp computation at import time (ACT022 —
it initializes a backend and burns compile time before main runs).
"""

from __future__ import annotations

import ast

from .core import FileContext, dotted_name, rule, walk_excluding_nested_functions

# Host-impure call targets: inside a traced function these execute once
# at trace time and bake a constant into the compiled computation.
IMPURE_CALLS = (
    "time.time",
    "time.time_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "os.urandom",
    "uuid.uuid4",
)
IMPURE_PREFIXES = (
    "random.",  # the stdlib module; jax.random resolves to "jax.random." and passes
    "numpy.random.",
)

# Calls that force a device->host transfer (or a dispatch-queue flush).
SYNC_ATTR_CALLS = {"item", "block_until_ready"}
SYNC_TARGETS = {
    "jax.device_get",
    "jax.block_until_ready",
    "numpy.asarray",
    "numpy.array",
}


def _jit_functions(ctx: FileContext) -> list[ast.AST]:
    """Function defs traced by JAX: decorated with jax.jit (bare, via
    functools.partial, or called with options), plus Pallas kernel
    bodies (functions passed as the first argument to pl.pallas_call)."""
    tree = ctx.tree
    assert tree is not None

    def is_jit_expr(node: ast.expr) -> bool:
        r = ctx.resolve(node)
        if r in ("jax.jit", "jax.pmap", "jax.vmap"):
            return True
        if isinstance(node, ast.Call):
            fr = ctx.resolve(node.func)
            if fr in ("functools.partial", "partial") and node.args:
                return is_jit_expr(node.args[0])
            return fr in ("jax.jit", "jax.pmap")
        return False

    jitted: list[ast.AST] = []
    kernel_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit_expr(d) for d in node.decorator_list):
                jitted.append(node)
        elif isinstance(node, ast.Call):
            r = ctx.resolve(node.func)
            if r is not None and r.endswith("pallas_call") and node.args:
                name = dotted_name(node.args[0])
                if name is not None:
                    kernel_names.add(name.split(".")[-1])
    if kernel_names:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name in kernel_names
                and node not in jitted
            ):
                jitted.append(node)
    return jitted


@rule("ACT020", "impure-jit", "host-impure call inside a traced function")
def check_impure_jit(ctx: FileContext):
    if ctx.tree is None:
        return
    for fn in _jit_functions(ctx):
        # Nested defs ARE included: they run under the same trace.
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            if target is None:
                continue
            if target in IMPURE_CALLS or any(
                target.startswith(p) for p in IMPURE_PREFIXES
            ):
                yield ctx.finding(
                    node,
                    "ACT020",
                    f"impure call '{target}' inside traced function "
                    f"'{fn.name}': it runs once at trace time and bakes a "
                    "constant into the compiled computation",
                )


@rule("ACT021", "device-sync-in-loop", "device sync inside a host loop (sim/ops)")
def check_sync_in_loop(ctx: FileContext):
    if ctx.tree is None or not ({"sim", "ops"} & ctx.domains):
        return
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        # int()/float() of a loop variable iterates a Python container —
        # pure host work, no device queue involved. Collect every For
        # target within this loop's subtree so `for ln in lines:
        # int(ln)` never needs a suppression.
        loop_vars = {
            x.id
            for n in ast.walk(loop)
            if isinstance(n, (ast.For, ast.AsyncFor))
            for x in ast.walk(n.target)
            if isinstance(x, ast.Name)
        }
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            if target in SYNC_TARGETS:
                yield ctx.finding(
                    node,
                    "ACT021",
                    f"'{target}' in a host loop forces a device sync per "
                    "iteration (hoist it, or buffer device scalars and "
                    "convert after the loop)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_ATTR_CALLS
                and not node.args
            ):
                yield ctx.finding(
                    node,
                    "ACT021",
                    f"'.{node.func.attr}()' in a host loop forces a device "
                    "sync per iteration (buffer and convert after the loop)",
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int")
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)
                and not (
                    isinstance(node.args[0], ast.Name)
                    and node.args[0].id in loop_vars
                )
            ):
                yield ctx.finding(
                    node,
                    "ACT021",
                    f"'{node.func.id}(...)' on an array in a host loop "
                    "blocks on the device queue (poll at chunk boundaries "
                    "or buffer device scalars)",
                )


@rule(
    "ACT023",
    "lane-sync-in-sweep-loop",
    "per-lane host sync on a lane-indexed array inside a sweep loop",
)
def check_lane_sync_in_sweep_loop(ctx: FileContext):
    """The sweep engine's failure mode: a host loop over lanes that
    converts ONE element of a lane-axis device array per iteration
    (``int(first[lane])``, ``np.asarray(spread[i])``, ``x[lane].item()``)
    — S device syncs where one conversion of the whole array after the
    loop would do (sim/sweep.py's idiom). Syntactic heuristic: the
    synced expression is a Subscript indexed by the loop variable."""
    if ctx.tree is None or not ({"sim", "ops"} & ctx.domains):
        return
    seen: set[tuple[int, int]] = set()
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor)):
            continue
        loop_vars = {
            x.id for x in ast.walk(loop.target) if isinstance(x, ast.Name)
        }
        if not loop_vars:
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            synced: ast.expr | None = None
            label = target
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("int", "float")
                and len(node.args) == 1
            ):
                synced, label = node.args[0], f"{node.func.id}(...)"
            elif target in SYNC_TARGETS and node.args:
                synced, label = node.args[0], f"{target}(...)"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("item", "tolist")
                and not node.args
            ):
                synced, label = node.func.value, f".{node.func.attr}()"
            if not isinstance(synced, ast.Subscript):
                continue
            if not any(
                isinstance(x, ast.Name) and x.id in loop_vars
                for x in ast.walk(synced.slice)
            ):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:  # nested loops: report each call site once
                continue
            seen.add(key)
            yield ctx.finding(
                node,
                "ACT023",
                f"'{label}' on a lane-indexed array inside a sweep loop "
                "syncs the device once per lane (convert the whole lane "
                "axis once, after the loop)",
            )


@rule(
    "ACT024",
    "pallas-kernel-untested",
    "pl.pallas_call site without a registered XLA differential test",
)
def check_pallas_differential_test(ctx: FileContext):
    """Every Pallas kernel in this repo is pinned bit-identical to the
    XLA path by an interpret-mode differential suite (the `make
    kernel-parity` gate) — a kernel without one is exactly how a silent
    numerics drift ships. The registration convention is textual and
    checkable: the function wrapping the ``pl.pallas_call`` (or its
    module docstring) must reference an EXISTING ``tests/test_*.py``
    file. Scoped to the ops domain (kernels live there; fixtures opt in
    via ``# analyze-domain: ops``)."""
    import re

    from .core import REPO_ROOT

    if ctx.tree is None or "ops" not in ctx.domains:
        return
    test_ref = re.compile(r"tests/test_[A-Za-z0-9_]+\.py")

    def has_registered_test(doc: str | None) -> bool:
        for ref in test_ref.findall(doc or ""):
            if (REPO_ROOT / ref).is_file():
                return True
        return False

    mod_ok = has_registered_test(ast.get_docstring(ctx.tree))
    if mod_ok:
        return
    funcs = [
        fn
        for fn in ast.walk(ctx.tree)
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    # Pass 1: a credited function covers every call site under it
    # (nested defs included) — collected first so an uncredited OUTER
    # function cannot flag a credited inner one's site.
    seen: set[tuple[int, int]] = set()
    for fn in funcs:
        if has_registered_test(ast.get_docstring(fn)):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    seen.add((node.lineno, node.col_offset))
    for fn in funcs:
        if has_registered_test(ast.get_docstring(fn)):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue  # nested defs: report each call site once
            target = ctx.resolve(node.func)
            if target is not None and target.endswith("pallas_call"):
                seen.add(key)
                yield ctx.finding(
                    node,
                    "ACT024",
                    f"'pl.pallas_call' in '{fn.name}' has no registered "
                    "XLA differential test (reference an existing "
                    "tests/test_*.py in the function or module "
                    "docstring; see docs/static-analysis.md)",
                )


@rule("ACT022", "import-time-jnp", "jnp computation at module import time")
def check_import_time_jnp(ctx: FileContext):
    tree = ctx.tree
    if tree is None:
        return
    for stmt in tree.body:
        if isinstance(
            stmt,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Import, ast.ImportFrom),
        ):
            continue
        # Only code that RUNS at import time counts: a def nested under
        # a module-level if/try (the version-compat pattern) is lazy.
        for node in walk_excluding_nested_functions([stmt]):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            if (
                target is not None
                and target.startswith("jax.numpy.")
                and target != "jax.numpy.dtype"  # metadata, no device op
            ):
                yield ctx.finding(
                    node,
                    "ACT022",
                    f"'{target}' at module import time initializes a "
                    "backend before main() (build constants lazily or "
                    "inside the traced function)",
                )


# -- ACT025: silent widening of packed/narrow state fields --------------------
#
# The memory ladder (docs/sim.md) earns its B/pair figures only while
# the packed/narrow state matrices stay packed in HBM: one stray
# `state.w.astype(jnp.int32)` materializes the wide matrix and quietly
# un-earns the rung. Every DELIBERATE widen therefore routes through the
# sanctioned helpers in sim/packed.py (watermarks_i32, unpack_u4,
# imean_f32, ...); this rule flags widening conversions applied to the
# packed-state field NAMES anywhere else in the sim/ops domains.

WIDEN_TARGET_NAMES = {"w", "hb_known", "imean"}
WIDEN_DTYPES = {"int32", "int64", "float32", "float64"}
_SANCTIONED_FILE_SUFFIX = "sim/packed.py"


def _trailing_name(node: ast.AST) -> str | None:
    """`state.w` -> "w", `w` -> "w", anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_widen_dtype(arg: ast.AST, ctx: FileContext) -> bool:
    """Whether an astype/constructor argument names one of the wide
    dtypes (jnp.int32 / np.float32 / "int32" / int). Dtype expressions
    like `out_ref.dtype` are matching-width copies, not widens."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value in WIDEN_DTYPES
    d = dotted_name(arg)
    if d is None:
        return False
    tail = d.rsplit(".", 1)[-1]
    return tail in WIDEN_DTYPES or d in ("int", "float")


@rule(
    "ACT025",
    "silent-widen-packed-state",
    "widening conversion on a packed state field outside the sanctioned helpers",
)
def check_silent_widen_packed_state(ctx: FileContext):
    if ctx.tree is None or not ({"sim", "ops"} & ctx.domains):
        return
    if ctx.relpath.replace("\\", "/").endswith(_SANCTIONED_FILE_SUFFIX):
        return  # THE sanctioned widen module
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        # Form 1: <target>.astype(<wide dtype>)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
        ):
            name = _trailing_name(node.func.value)
            if name in WIDEN_TARGET_NAMES and _is_widen_dtype(
                node.args[0], ctx
            ):
                yield ctx.finding(
                    node,
                    "ACT025",
                    f"'{name}.astype(...)' widens a packed/narrow state "
                    "field in place — route through the sanctioned "
                    "helpers in sim/packed.py (watermarks_i32 / "
                    "imean_f32 / unpack_u4) so the wide form never "
                    "lands in HBM unaudited",
                )
            continue
        # Form 2: jnp.int32(<target>) / np.float32(<target>)
        target = ctx.resolve(node.func)
        if (
            target is not None
            and target.rsplit(".", 1)[-1] in WIDEN_DTYPES
            and len(node.args) == 1
            and _trailing_name(node.args[0]) in WIDEN_TARGET_NAMES
        ):
            yield ctx.finding(
                node,
                "ACT025",
                f"'{target}' promotes packed state field "
                f"'{_trailing_name(node.args[0])}' — use the sanctioned "
                "widen helpers in sim/packed.py",
            )


# -- ACT029: packed matrix widened in HBM (ops/ hot paths) --------------------
#
# The packed rungs' whole claim is "the wide matrix never exists in
# HBM": the XLA hot path computes on the nibbles inside the fusion, and
# the Pallas pairs kernel widens per 8-row tile in VMEM only. A call to
# the unpack codecs (sim/packed.unpack_u4 / unpack_bits / residuals_u4)
# from an ops/ module OUTSIDE a kernel body therefore materializes the
# full wide matrix on the hot path — exactly the transient the packed
# rungs exist to avoid (and the one sim/memory.plan stopped charging
# for kernel-served rungs). Enforced the same way ACT025 guards sim/:
# the sanctioned module (sim/packed.py) and kernel bodies (functions
# named *_kernel — the pallas_call targets, which widen in VMEM by
# construction) are exempt; everything else in the ops domain must
# route through the value-level helpers (watermarks_i32 and friends)
# off the hot path, or stay packed.

UNPACK_HELPER_NAMES = {"unpack_u4", "unpack_bits", "residuals_u4"}


def _enclosing_function_names(tree: ast.Module) -> dict[int, tuple[str, ...]]:
    """Map each Call node id to the names of ALL its enclosing
    FunctionDefs, outermost first (() at module scope). The whole chain
    matters: kernel bodies in this repo do their per-tile work inside
    nested closures (``def body(s, _)`` inside ``_pairs_kernel``), and
    a closure's decode is still a VMEM transient of the kernel that
    owns it."""
    out: dict[int, tuple[str, ...]] = {}

    def visit(node: ast.AST, stack: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, stack + (child.name,))
            else:
                if isinstance(child, ast.Call):
                    out[id(child)] = stack
                visit(child, stack)

    visit(tree, ())
    return out


@rule(
    "ACT029",
    "packed-widen-in-hbm",
    "full packed matrix widened outside kernels and the sanctioned helpers",
)
def check_packed_widen_in_hbm(ctx: FileContext):
    if ctx.tree is None or "ops" not in ctx.domains:
        return
    if ctx.relpath.replace("\\", "/").endswith(_SANCTIONED_FILE_SUFFIX):
        return  # THE sanctioned widen module
    owners = _enclosing_function_names(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        target = ctx.resolve(node.func)
        tail = (target or "").rsplit(".", 1)[-1]
        if tail not in UNPACK_HELPER_NAMES:
            continue
        chain = owners.get(id(node), ())
        if any(name.endswith("_kernel") for name in chain):
            # Kernel bodies widen per tile in VMEM by construction —
            # the decode never round-trips through HBM there; closures
            # nested inside a kernel body are part of that body.
            continue
        where = f"in '{chain[-1]}'" if chain else "at module scope"
        yield ctx.finding(
            node,
            "ACT029",
            f"'{tail}' {where} materializes the full wide matrix in "
            "HBM on an ops/ path — compute on the nibbles in place "
            "(the byte-space algebra), run it inside a *_kernel body, "
            "or move the decode off the hot path via the sanctioned "
            "value helpers in sim/packed.py",
        )
