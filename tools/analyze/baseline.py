"""Baseline (grandfathering) support.

A committed baseline file lists pre-existing findings by fingerprint
(path, code, message — no line numbers, so unrelated edits don't churn
it). Findings matching a baseline entry are reported as ``baselined``
and do not fail the run; anything NEW does. The intended workflow:

- ``python -m tools.analyze --write-baseline PATH...`` snapshots today's
  findings; commit the file.
- Fix a grandfathered finding -> its entry goes stale; the run reports
  the stale count (informational) and ``--write-baseline`` prunes it.
- Never baseline a finding you just introduced: baselines are for
  adopting the tool on an existing codebase, suppressions (``# noqa:
  ACT0xx -- why``) are for judged-intentional code.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

from .core import Finding

SCHEMA = "aiocluster-analyze-baseline/1"
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load(path: Path) -> Counter:
    """Multiset of grandfathered fingerprints (an entry absorbs one
    occurrence per ``count``)."""
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("schema") != SCHEMA:
        raise ValueError(f"{path}: unknown baseline schema {data.get('schema')!r}")
    counts: Counter = Counter()
    for e in data["findings"]:
        counts[(e["path"], e["code"], e["message"])] += int(e.get("count", 1))
    return counts


def apply(findings: list[Finding], baseline: Counter) -> int:
    """Mark matching non-suppressed findings ``baselined`` (consuming
    baseline budget); returns the number of stale (unconsumed) entries."""
    budget = Counter(baseline)
    for f in findings:
        if f.status != "new":
            continue
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
            f.status = "baselined"
    return sum(n for n in budget.values() if n > 0)


def write(path: Path, findings: list[Finding]) -> int:
    """Snapshot every non-suppressed finding as the new baseline;
    returns the entry count. Entries are sorted and count-folded so the
    file diffs cleanly."""
    counts: Counter = Counter(
        f.fingerprint() for f in findings if f.status != "suppressed"
    )
    entries = [
        {"path": p, "code": c, "message": m, **({"count": n} if n > 1 else {})}
        for (p, c, m), n in sorted(counts.items())
    ]
    payload = {"schema": SCHEMA, "findings": entries}
    path.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")
    return sum(counts.values())
