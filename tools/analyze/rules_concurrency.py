"""ACT05x — flow-sensitive concurrency analysis (docs/static-analysis.md).

Every hard runtime bug this repo has shipped was an *interleaving* bug:
read shared state, await (the scheduler runs someone else), then act on
the stale read. These rules run on the per-function CFGs from flow.py
and the resolved class/attr tables from symbols.py, scoped to the
domains where the event loop actually interleaves (``runtime/``,
``serve/``, ``obs/`` — fixtures opt in with ``# analyze-domain:``).

- ACT050 stale-read-across-await: a shared ``self.<attr>`` is rebound
  on a path where the most recent access was a READ separated from this
  write by a suspension point — the non-reentrant teardown/guard shape
  (``if self._t: ... await ... self._t = None``). Fix by swapping to a
  local before the await or re-reading after it.
- ACT051 critical-section discipline: (a) a plain ``self.<flag> = True``
  guard held across an await whose reset is not in a covering
  ``finally``; (b) a field that one method mutates under ``async with
  self.<lock>`` mutated elsewhere outside any such section.
- ACT052 paired-resource flow: (a) a pool ``acquire()``/``borrow()``
  result that reaches some exit path neither released, discarded,
  closed, returned, nor handed off; (b) ``self.<n> += 1`` before an
  await whose paired ``-= 1`` is not in a covering ``finally``.
- ACT053 broad-except-on-hot-path: a bare/``Exception`` handler in
  ``runtime/``/``serve/`` that neither re-raises, logs, nor counts —
  silent failure absorption in the gossip loop.

The family starts with an EMPTY baseline: every repo finding is fixed
or carries a justified ``# noqa: ACT05x -- why``.
"""

from __future__ import annotations

import ast

from .core import FileContext, dotted_name, rule
from .flow import build_cfg, dataflow, _is_self_attr
from .symbols import LOCK_TYPES, ClassInfo, SymbolGraph

#: Where the asyncio event loop interleaves this repo's shared state.
HOT_DOMAINS = frozenset({"runtime", "serve", "obs"})
#: ACT053's narrower scope: the gossip/serve hot path proper.
EXC_DOMAINS = frozenset({"runtime", "serve"})

_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "error", "exception", "critical", "log"}
)
_COUNT_METHODS = frozenset(
    {"inc", "observe", "set", "labels", "note", "_note", "count", "record", "add"}
)
_ACQUIRE_METHODS = frozenset({"acquire", "borrow"})
_SETTLE_SELF_METHODS = frozenset({"close", "release", "aclose", "discard"})


def _graph(ctx: FileContext) -> SymbolGraph:
    """Whole-repo graph when the two-phase engine attached one; a
    single-file graph otherwise (fixture tests analyze one file)."""
    if ctx.symbols is None:
        ctx.symbols = SymbolGraph.build([ctx])
    return ctx.symbols


def _classes(ctx: FileContext) -> list[ClassInfo]:
    mod = _graph(ctx).by_relpath.get(ctx.relpath)
    return list(mod.classes.values()) if mod else []


def _method_walk(meth: ast.AST):
    """Walk a method body without entering nested function/class scopes
    (their statements execute elsewhere)."""
    stack = list(ast.iter_child_nodes(meth))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _suspensions(meth: ast.AST) -> list[ast.AST]:
    out = [n for n in _method_walk(meth)
           if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))]
    return out


def _try_spans(meth: ast.AST) -> list[tuple[set[int], set[int]]]:
    """(ids of nodes in body+handlers+orelse, ids in finalbody) for each
    Try under the method — containment currency for the finally checks."""
    spans = []
    for n in _method_walk(meth):
        if isinstance(n, ast.Try) and n.finalbody:
            span: set[int] = set()
            for part in (n.body, n.handlers, n.orelse):
                for s in part:
                    span.update(id(x) for x in ast.walk(s))
            fin: set[int] = set()
            for s in n.finalbody:
                fin.update(id(x) for x in ast.walk(s))
            spans.append((span, fin))
    return spans


def _finally_covers(meth, anchor, awaits_after, resets) -> bool:
    """True when some ``finally`` contains a reset AND its Try contains
    either the anchor statement or one of the awaits after it — i.e. the
    reset runs no matter how the suspended region exits."""
    for span, fin in _try_spans(meth):
        if not any(id(r) in fin for r in resets):
            continue
        if id(anchor) in span or any(id(a) in span for a in awaits_after):
            return True
    return False


# ---------------------------------------------------------------------------
# ACT050 — stale read across await
# ---------------------------------------------------------------------------

_NONE, _WRITTEN, _FRESH, _STALE = 0, 1, 2, 3


def _act050_transfer(collect):
    def transfer(state, block):
        for ev in block.events:
            kind = ev[0]
            if kind == "self_read":
                state[ev[1]] = _FRESH
            elif kind == "await":
                for a, v in state.items():
                    if v == _FRESH:
                        state[a] = _STALE
            elif kind == "self_write":
                if state.get(ev[1], _NONE) == _STALE and collect is not None:
                    collect.add((ev[1], ev[2]))
                state[ev[1]] = _WRITTEN
            elif kind == "self_rw":
                state[ev[1]] = _WRITTEN
        return state

    return transfer


def _act050_merge(a, b):
    return {k: max(a.get(k, _NONE), b.get(k, _NONE)) for k in set(a) | set(b)}


@rule(
    "ACT050",
    "stale-read-across-await",
    "shared self attribute rebound after an await that follows the read "
    "it acted on (guard/teardown races: swap to a local before the await)",
)
def act050(ctx: FileContext):
    if ctx.tree is None or not (ctx.domains & HOT_DOMAINS):
        return
    for ci in _classes(ctx):
        for mname, meth in ci.methods.items():
            if not isinstance(meth, ast.AsyncFunctionDef):
                continue
            cfg = build_cfg(meth)
            states = dataflow(cfg, {}, _act050_transfer(None), _act050_merge)
            collect: set[tuple[str, ast.AST]] = set()
            tr = _act050_transfer(collect)
            for bid, st in states.items():
                tr(dict(st), cfg.blocks[bid])
            for attr, node in sorted(collect, key=lambda t: (t[0], t[1].lineno)):
                info = ci.attrs.get(attr)
                if info is None or not info.shared:
                    continue  # single-method attrs have no second party
                yield ctx.finding(
                    node,
                    "ACT050",
                    f"stale read across await: {ci.qualname}.{mname}() rebinds "
                    f"self.{attr} after an await that follows the read it "
                    "acted on — swap to a local before the await or re-read "
                    "after it",
                )


# ---------------------------------------------------------------------------
# ACT051 — critical-section discipline
# ---------------------------------------------------------------------------

def _is_flag_assign(stmt: ast.stmt, value: bool) -> str | None:
    """attr name when stmt is ``self.<attr> = True/False``."""
    if (
        isinstance(stmt, ast.Assign)
        and len(stmt.targets) == 1
        and _is_self_attr(stmt.targets[0])
        and isinstance(stmt.value, ast.Constant)
        and stmt.value.value is value
    ):
        return stmt.targets[0].attr
    return None


@rule(
    "ACT051",
    "critical-section-discipline",
    "flag guard held across an await without a finally reset, or a "
    "lock-protected field mutated outside its async-with section",
)
def act051(ctx: FileContext):
    if ctx.tree is None or not (ctx.domains & HOT_DOMAINS):
        return
    for ci in _classes(ctx):
        yield from _act051_flags(ctx, ci)
        yield from _act051_locks(ctx, ci)


def _act051_flags(ctx: FileContext, ci: ClassInfo):
    for mname, meth in ci.methods.items():
        if not isinstance(meth, ast.AsyncFunctionDef):
            continue
        stmts = list(_method_walk(meth))
        sets = [(s, _is_flag_assign(s, True)) for s in stmts]
        sets = [(s, a) for s, a in sets if a]
        if not sets:
            continue
        # A reset inside an except handler that re-raises is the
        # latch-with-ROLLBACK idiom (undo the latch on failure, keep it
        # on success) — not the guard shape this rule polices.
        rollback_ids: set[int] = set()
        for n in _method_walk(meth):
            if isinstance(n, ast.ExceptHandler) and any(
                isinstance(x, ast.Raise) for s in n.body for x in ast.walk(s)
            ):
                for s in n.body:
                    rollback_ids.update(id(x) for x in ast.walk(s))
        resets_by_attr: dict[str, list[ast.stmt]] = {}
        for s in stmts:
            a = _is_flag_assign(s, False)
            if a and id(s) not in rollback_ids:
                resets_by_attr.setdefault(a, []).append(s)
        awaits = _suspensions(meth)
        for set_stmt, attr in sets:
            resets = resets_by_attr.get(attr)
            if not resets:
                continue  # no reset at all: a latch, not a guard
            after = [a for a in awaits if a.lineno > set_stmt.lineno]
            if not after:
                continue
            if _finally_covers(meth, set_stmt, after, resets):
                continue
            yield ctx.finding(
                set_stmt,
                "ACT051",
                f"flag guard leaks across await: {ci.qualname}.{mname}() sets "
                f"self.{attr} = True, suspends, and resets it outside any "
                "covering finally — an exception or cancellation leaves the "
                "guard latched",
            )


def _act051_locks(ctx: FileContext, ci: ClassInfo):
    lock_attrs = {
        name
        for name, a in ci.attrs.items()
        if (a.type in LOCK_TYPES)
        or ("lock" in name.lower() and a.written_in_init and a.type is None)
    }
    if not lock_attrs:
        return
    guarded: dict[str, set[str]] = {}  # field -> lock attrs seen guarding it
    writes: list[tuple[str, ast.AST, str, bool]] = []  # field, node, meth, locked
    for mname, meth in ci.methods.items():
        if mname == "__init__":
            continue
        locked_ids: dict[int, str] = {}
        for n in _method_walk(meth):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for it in n.items:
                    if _is_self_attr(it.context_expr) and it.context_expr.attr in lock_attrs:
                        for sub in ast.walk(n):
                            locked_ids[id(sub)] = it.context_expr.attr
        for n in _method_walk(meth):
            field = None
            if _is_self_attr(n) and isinstance(n.ctx, ast.Store):
                field = n.attr
            if field is None or field in lock_attrs:
                continue
            lock = locked_ids.get(id(n))
            if lock is not None:
                guarded.setdefault(field, set()).add(lock)
                writes.append((field, n, mname, True))
            else:
                writes.append((field, n, mname, False))
    for field, node, mname, locked in writes:
        if locked or field not in guarded:
            continue
        lock = sorted(guarded[field])[0]
        yield ctx.finding(
            node,
            "ACT051",
            f"lock-protected field mutated outside its critical section: "
            f"self.{field} is written under `async with self.{lock}` "
            f"elsewhere in {ci.qualname} but {mname}() mutates it unlocked",
        )


# ---------------------------------------------------------------------------
# ACT052 — paired-resource flow
# ---------------------------------------------------------------------------

def _pool_like(ctx: FileContext, ci: ClassInfo | None, recv: ast.AST) -> bool:
    graph = _graph(ctx)
    if _is_self_attr(recv) and ci is not None:
        t = graph.attr_type(ci, recv.attr)
        if t:
            if t.endswith("ConnectionPool") or t.endswith("Pool"):
                return True
            target = graph.class_info(t)
            if target is not None and (
                target.has_methods("release") or target.has_methods("discard")
            ):
                return True
        return "pool" in recv.attr.lower()
    d = dotted_name(recv)
    return bool(d) and "pool" in d.lower()


def _acquires(func: ast.AST, ctx: FileContext, ci: ClassInfo | None):
    """{statement-id: (var, stmt)} for ``v = await <pool>.acquire(...)``."""
    out: dict[int, tuple[str, ast.stmt]] = {}
    for n in _method_walk(func):
        if (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
            and isinstance(n.value, ast.Await)
            and isinstance(n.value.value, ast.Call)
            and isinstance(n.value.value.func, ast.Attribute)
            and n.value.value.func.attr in _ACQUIRE_METHODS
            and _pool_like(ctx, ci, n.value.value.func.value)
        ):
            out[id(n)] = (n.targets[0].id, n)
    return out


def _settles(stmt: ast.AST, var: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call):
            args = list(n.args) + [k.value for k in n.keywords]
            if any(isinstance(a, ast.Name) and a.id == var for a in args):
                return True  # released/discarded/handed off
            if (
                isinstance(n.func, ast.Attribute)
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == var
                and n.func.attr in _SETTLE_SELF_METHODS
            ):
                return True
        elif isinstance(n, ast.Return) and n.value is not None:
            if any(isinstance(x, ast.Name) and x.id == var
                   for x in ast.walk(n.value)):
                return True  # ownership transferred to the caller
        elif isinstance(n, ast.Assign):
            if isinstance(n.value, ast.Name) and n.value.id == var:
                return True  # stored/aliased verbatim: ownership moved
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            if any(isinstance(it.context_expr, ast.Name)
                   and it.context_expr.id == var for it in n.items):
                return True  # a context manager settles it
    return False


@rule(
    "ACT052",
    "paired-resource-flow",
    "pool borrow not settled (release/discard/transfer) on every exit "
    "path, or a counter increment whose decrement isn't finally-covered",
)
def act052(ctx: FileContext):
    if ctx.tree is None or not (ctx.domains & HOT_DOMAINS):
        return
    graph = _graph(ctx)
    mod = graph.by_relpath.get(ctx.relpath)
    funcs: list[tuple[ClassInfo | None, str, ast.AST]] = []
    if mod:
        for ci in mod.classes.values():
            for mname, meth in ci.methods.items():
                funcs.append((ci, f"{ci.qualname}.{mname}", meth))
    if ctx.tree:
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append((None, stmt.name, stmt))
    for ci, label, func in funcs:
        if isinstance(func, ast.AsyncFunctionDef):
            yield from _act052_borrows(ctx, ci, label, func)
            yield from _act052_counters(ctx, ci, label, func)


def _act052_borrows(ctx, ci, label, func):
    acquires = _acquires(func, ctx, ci)
    if not acquires:
        return
    cfg = build_cfg(func)

    def transfer(state, block):
        for ev in block.events:
            if ev[0] != "stmt":
                continue
            stmt = ev[1]
            acq = acquires.get(id(stmt))
            if acq is not None:
                state[acq[0]] = 1
                continue
            for var, v in list(state.items()):
                if v and _settles(stmt, var):
                    state[var] = 0
        return state

    def merge(a, b):
        return {k: max(a.get(k, 0), b.get(k, 0)) for k in set(a) | set(b)}

    states = dataflow(cfg, {}, transfer, merge)
    at_exit = states.get(cfg.exit, {})
    leaked = {v for v, s in at_exit.items() if s}
    for var, stmt in acquires.values():
        if var in leaked:
            yield ctx.finding(
                stmt,
                "ACT052",
                f"borrowed resource can leak: {label}() binds `{var}` from a "
                "pool acquire but some exit path neither releases, discards, "
                "closes, returns, nor hands it off — settle it in a finally",
            )


def _act052_counters(ctx, ci, label, func):
    incs: list[tuple[ast.AugAssign, str]] = []
    decs: dict[str, list[ast.stmt]] = {}
    for n in _method_walk(func):
        if isinstance(n, ast.AugAssign) and _is_self_attr(n.target):
            if isinstance(n.op, ast.Add):
                incs.append((n, n.target.attr))
            elif isinstance(n.op, ast.Sub):
                decs.setdefault(n.target.attr, []).append(n)
    if not incs:
        return
    awaits = _suspensions(func)
    for inc, attr in incs:
        resets = decs.get(attr)
        if not resets:
            continue  # no paired decrement in this function
        after = [a for a in awaits if a.lineno > inc.lineno]
        if not after:
            continue
        if _finally_covers(func, inc, after, resets):
            continue
        yield ctx.finding(
            inc,
            "ACT052",
            f"counter pairing leaks across await: {label}() increments "
            f"self.{attr}, suspends, and decrements it outside any covering "
            "finally — an exception leaves the counter high forever",
        )


# ---------------------------------------------------------------------------
# ACT053 — broad except on the hot path
# ---------------------------------------------------------------------------

def _broad_handler(t: ast.expr | None) -> str | None:
    if t is None:
        return "bare except"
    if isinstance(t, ast.Tuple):
        for el in t.elts:
            got = _broad_handler(el)
            if got:
                return got
        return None
    d = dotted_name(t)
    if d in ("Exception", "BaseException") or (
        d and d.split(".")[-1] in ("Exception", "BaseException")
    ):
        return f"except {d}"
    return None


def _handler_accounted(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            meth = n.func.attr
            if meth == "exception":
                return True  # logger.exception(...)
            recv = (dotted_name(n.func.value) or "").lower()
            if meth in _LOG_METHODS and "log" in recv:
                return True
            if meth in _COUNT_METHODS:
                return True
    return False


@rule(
    "ACT053",
    "broad-except-on-hot-path",
    "bare/Exception handler in runtime//serve/ that neither re-raises, "
    "logs, nor counts — silent failure absorption in the gossip loop",
)
def act053(ctx: FileContext):
    if ctx.tree is None or not (ctx.domains & EXC_DOMAINS):
        return
    for n in ast.walk(ctx.tree):
        if not isinstance(n, ast.ExceptHandler):
            continue
        shape = _broad_handler(n.type)
        if shape is None:
            continue
        if _handler_accounted(n):
            continue
        yield ctx.finding(
            n,
            "ACT053",
            f"{shape} on a hot path absorbs failures silently — re-raise, "
            "log, or count the error (or narrow the exception type)",
        )
