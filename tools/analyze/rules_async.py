"""ACT01x — async-safety.

The runtime's Syn→SynAck→Ack handshake lives entirely on one event
loop; the five rules here target the bug classes that silently sink
such a loop: blocking it (ACT010), forgetting to await (ACT011),
letting the GC collect an in-flight task (ACT012 — asyncio holds only a
weak reference to running tasks), swallowing cancellation so shutdown
hangs (ACT013), and leaking stream-writer transports by closing without
joining the close (ACT014 — the leak class a connection pool makes easy
to reintroduce).
"""

from __future__ import annotations

import ast

from .core import FileContext, dotted_name, rule, walk_excluding_nested_functions

# Fully-qualified call targets that block the calling thread. Resolution
# goes through the module's import map, so both ``time.sleep(...)`` and
# ``from time import sleep; sleep(...)`` match.
BLOCKING_CALLS = {
    "time.sleep": "time.sleep blocks the event loop (use asyncio.sleep)",
    "subprocess.run": "subprocess.run blocks (use asyncio.create_subprocess_exec)",
    "subprocess.call": "subprocess.call blocks (use asyncio.create_subprocess_exec)",
    "subprocess.check_call": "subprocess.check_call blocks (use asyncio subprocesses)",
    "subprocess.check_output": "subprocess.check_output blocks (use asyncio subprocesses)",
    "subprocess.Popen": "subprocess.Popen blocks on pipe I/O (use asyncio subprocesses)",
    "os.system": "os.system blocks (use asyncio.create_subprocess_shell)",
    "os.waitpid": "os.waitpid blocks (use asyncio child watchers)",
    "socket.create_connection": "blocking socket connect (use asyncio.open_connection)",
    "socket.getaddrinfo": "blocking DNS resolution (use loop.getaddrinfo)",
    "socket.gethostbyname": "blocking DNS resolution (use loop.getaddrinfo)",
    "requests.get": "requests blocks (use an async HTTP client or to_thread)",
    "requests.post": "requests blocks (use an async HTTP client or to_thread)",
    "requests.request": "requests blocks (use an async HTTP client or to_thread)",
    "urllib.request.urlopen": "urlopen blocks (use an async HTTP client or to_thread)",
    "open": "file open() blocks (wrap in asyncio.to_thread for slow media)",
}
# Synchronous-file-I/O method names: flagged on any receiver inside an
# async def (Path.read_text and friends).
BLOCKING_METHODS = {
    "read_text", "write_text", "read_bytes", "write_bytes",
}


def _is_cancelled_error(ctx: FileContext, node: ast.expr) -> bool:
    r = ctx.resolve(node)
    return r is not None and (
        r == "asyncio.CancelledError"
        or r.endswith(".CancelledError")
        or r == "CancelledError"
    )


@rule("ACT010", "blocking-call-in-async", "blocking call inside async def")
def check_blocking(ctx: FileContext):
    if ctx.tree is None:
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in walk_excluding_nested_functions(fn.body):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            if target in BLOCKING_CALLS:
                yield ctx.finding(
                    node,
                    "ACT010",
                    f"blocking call '{target}' in async def "
                    f"'{fn.name}': {BLOCKING_CALLS[target]}",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in BLOCKING_METHODS
            ):
                yield ctx.finding(
                    node,
                    "ACT010",
                    f"blocking file I/O '.{node.func.attr}()' in async def "
                    f"'{fn.name}' (wrap in asyncio.to_thread)",
                )


def _async_defs(tree: ast.Module):
    """(module-level async function names, class -> async method names)."""
    module_async = {
        n.name for n in tree.body if isinstance(n, ast.AsyncFunctionDef)
    }
    class_async: dict[str, set[str]] = {}
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            class_async[cls.name] = {
                n.name for n in cls.body if isinstance(n, ast.AsyncFunctionDef)
            }
    return module_async, class_async


def _local_bindings(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    """Names a function scope binds: parameters plus anything assigned
    inside it (a binding shadows a module-level async def of the same
    name, so a bare call to it is NOT the coroutine)."""
    a = fn.args
    names = {
        p.arg
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)
    }
    for v in (a.vararg, a.kwarg):
        if v is not None:
            names.add(v.arg)
    for n in walk_excluding_nested_functions(fn.body):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                names |= {x.id for x in ast.walk(t) if isinstance(x, ast.Name)}
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign, ast.For, ast.AsyncFor)):
            names |= {
                x.id for x in ast.walk(n.target) if isinstance(x, ast.Name)
            }
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    names |= {
                        x.id
                        for x in ast.walk(item.optional_vars)
                        if isinstance(x, ast.Name)
                    }
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(n.name)
    return names


@rule("ACT011", "unawaited-coroutine", "coroutine called but never awaited")
def check_unawaited(ctx: FileContext):
    if ctx.tree is None:
        return
    module_async, class_async = _async_defs(ctx.tree)

    def scan_scope(body: list[ast.stmt], shadowed: frozenset[str]):
        nested: list[ast.AST] = []
        for node in walk_excluding_nested_functions(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                nested.append(node)
            elif (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in module_async
                and node.value.func.id not in shadowed
            ):
                yield ctx.finding(
                    node,
                    "ACT011",
                    f"coroutine '{node.value.func.id}()' is never awaited "
                    "(await it, or schedule it with asyncio.create_task and "
                    "retain the task)",
                )
        for child in nested:
            if isinstance(child, ast.ClassDef):
                yield from scan_scope(child.body, shadowed)
            else:
                yield from scan_scope(
                    child.body, shadowed | _local_bindings(child)
                )

    yield from scan_scope(ctx.tree.body, frozenset())
    # Bare-statement self.<async method>() within the defining class.
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        async_methods = class_async.get(cls.name, set())
        for node in ast.walk(cls):
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id == "self"
                and node.value.func.attr in async_methods
            ):
                yield ctx.finding(
                    node,
                    "ACT011",
                    f"coroutine 'self.{node.value.func.attr}()' is never "
                    "awaited (await it, or schedule it with "
                    "asyncio.create_task and retain the task)",
                )


@rule("ACT012", "dropped-task", "task created but reference dropped")
def check_dropped_task(ctx: FileContext):
    if ctx.tree is None:
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        target = ctx.resolve(call.func)
        is_spawn = target in ("asyncio.create_task", "asyncio.ensure_future")
        if not is_spawn and isinstance(call.func, ast.Attribute):
            # loop.create_task(...) / self._loop.create_task(...):
            # same weak-reference hazard. (TaskGroup.create_task retains
            # its tasks; group receivers are conventionally named 'tg'
            # or 'group' — not matched here.)
            recv = ctx.resolve(call.func.value) or ""
            is_spawn = call.func.attr == "create_task" and "loop" in recv.lower()
        if is_spawn:
            yield ctx.finding(
                node,
                "ACT012",
                "task reference dropped: asyncio keeps only a weak ref to "
                "running tasks — retain the result (and cancel it on close)",
            )


def _handler_reraises(node: ast.ExceptHandler) -> bool:
    return any(
        isinstance(n, ast.Raise)
        for n in walk_excluding_nested_functions(node.body)
    )


def _receiver_is_writer(dotted: str | None) -> bool:
    """True for receivers that name an asyncio StreamWriter by
    convention: the final path segment contains 'writer' (``writer``,
    ``self._writer``, ``conn.writer`` …)."""
    return dotted is not None and "writer" in dotted.rsplit(".", 1)[-1].lower()


@rule("ACT014", "unjoined-writer-close", "writer.close() without awaited wait_closed()")
def check_unjoined_writer_close(ctx: FileContext):
    """``StreamWriter.close()`` only *schedules* the transport teardown;
    without an awaited ``wait_closed()`` the socket (and any buffered
    bytes) linger until the GC gets around to it — per-handshake that is
    an fd leak, and exactly what a connection pool's borrow/discard
    paths make easy to reintroduce. Flags a ``<writer>.close()``
    statement in any function whose scope never awaits
    ``<same receiver>.wait_closed()`` (wrapping the await in
    ``contextlib.suppress`` or ``wait_for`` still counts)."""
    if ctx.tree is None:
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        closes: list[tuple[ast.AST, str]] = []
        waited: set[str] = set()
        for node in walk_excluding_nested_functions(fn.body):
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "close"
            ):
                recv = dotted_name(node.value.func.value)
                if _receiver_is_writer(recv):
                    closes.append((node, recv))
            elif isinstance(node, ast.Await):
                for sub in ast.walk(node.value):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "wait_closed"
                    ):
                        recv = dotted_name(sub.func.value)
                        if recv is not None:
                            waited.add(recv)
        for node, recv in closes:
            if recv not in waited:
                yield ctx.finding(
                    node,
                    "ACT014",
                    f"'{recv}.close()' never joined: await "
                    f"'{recv}.wait_closed()' in the same scope or the "
                    "transport (and its fd) leaks until GC",
                )


@rule("ACT026", "unbounded-asyncio-queue", "asyncio.Queue() without maxsize in runtime/serve")
def check_unbounded_queue(ctx: FileContext):
    """The runtime's dispatch discipline (HookDispatcher, the serve
    tier's watch hub): every ``asyncio.Queue`` between a producer that
    cannot block and a consumer that can lag must be BOUNDED, with the
    overflow dropped and counted — an unbounded queue turns one slow
    consumer into unbounded process memory. Flags ``asyncio.Queue()``
    constructed with no ``maxsize`` (or a literal ``maxsize`` <= 0 —
    asyncio treats ANY non-positive maxsize as infinite, so
    ``Queue(-1)``, the unbounded idiom of other queue APIs, is just as
    flagged as ``Queue(0)``) inside the runtime/ and serve/ trees. A
    maxsize passed as a variable is accepted — boundedness is then the
    binding site's contract."""
    if ctx.tree is None or not ({"runtime", "serve"} & ctx.domains):
        return

    def literal_maxsize(expr: ast.expr) -> int | float | None:
        # -1 parses as UnaryOp(USub, Constant(1)), not Constant(-1).
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
            inner = literal_maxsize(expr.operand)
            return None if inner is None else -inner
        if (
            isinstance(expr, ast.Constant)
            and isinstance(expr.value, (int, float))
            and not isinstance(expr.value, bool)
        ):
            return expr.value
        return None

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        target = ctx.resolve(node.func)
        if target not in (
            "asyncio.Queue",
            "asyncio.LifoQueue",
            "asyncio.PriorityQueue",
        ):
            continue
        if node.args:
            size = literal_maxsize(node.args[0])
            unbounded = size is not None and size <= 0
        else:
            kw = next(
                (k for k in node.keywords if k.arg == "maxsize"), None
            )
            if kw is None:
                unbounded = True
            else:
                size = literal_maxsize(kw.value)
                unbounded = size is not None and size <= 0
        if unbounded:
            yield ctx.finding(
                node,
                "ACT026",
                f"unbounded {target.rsplit('.', 1)[-1]}: pass a nonzero "
                "maxsize and count drops — one lagging consumer must "
                "degrade (drop/resync), not grow process memory",
            )


@rule("ACT027", "fixed-sleep-retry", "retry loop sleeps a constant with no backoff")
def check_fixed_sleep_retry(ctx: FileContext):
    """The overload layer's retry discipline (runtime/health.py,
    docs/robustness.md): a retry loop that sleeps a CONSTANT between
    attempts hammers a struggling peer at a fixed cadence — and a fleet
    of such loops thunders in phase. Flags a ``while``/``for`` loop in
    the runtime/ or serve/ trees whose body contains BOTH a
    ``try``/``except`` (the retry shape: the failure is absorbed and
    the loop goes around) AND an awaited ``asyncio.sleep`` whose delay
    is a numeric literal. A delay held in a variable or expression is
    accepted — growth/jitter then lives at the binding site (the
    decorrelated-jitter backoff the breaker uses); a constant cannot
    back off by construction. Cadence loops without a try (pollers,
    probes) are out of scope."""
    if ctx.tree is None or not ({"runtime", "serve"} & ctx.domains):
        return

    def is_const_delay(expr: ast.expr) -> bool:
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
            return is_const_delay(expr.operand)
        # sleep(0) is the canonical cooperative-yield idiom, not a
        # retry cadence — a zero delay cannot thunder.
        return (
            isinstance(expr, ast.Constant)
            and isinstance(expr.value, (int, float))
            and not isinstance(expr.value, bool)
            and expr.value != 0
        )

    flagged: set[ast.AST] = set()
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
            continue
        body = walk_excluding_nested_functions(loop.body)
        has_try = False
        sleeps: list[ast.AST] = []
        for node in body:
            if isinstance(node, ast.Try):
                has_try = True
            elif isinstance(node, ast.Await) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                if (
                    ctx.resolve(call.func) == "asyncio.sleep"
                    and call.args
                    and is_const_delay(call.args[0])
                ):
                    sleeps.append(node)
        if not has_try:
            continue
        for node in sleeps:
            if node not in flagged:  # nested loops walk the same body
                flagged.add(node)
                yield ctx.finding(
                    node,
                    "ACT027",
                    "fixed-sleep retry loop: a constant asyncio.sleep "
                    "between attempts retries at full cadence forever — "
                    "use exponential backoff with (decorrelated) jitter, "
                    "or the peer circuit breaker (runtime/health.py)",
                )


@rule("ACT028", "non-atomic-state-write", "state file written in place without atomic replace")
def check_non_atomic_state_write(ctx: FileContext):
    """The durability layer's write discipline (runtime/persist.py,
    docs/robustness.md): a state file opened ``"w"``/``"wb"`` on its
    FINAL path is torn by any crash mid-write — the next boot reads
    half a file where the tmp + fsync + ``os.replace`` idiom would have
    left the previous complete version. Flags ``open(path, "w"|"wb")``
    in the runtime/ or serve/ trees when (a) the path expression does
    not name a temporary (no ``tmp`` in any name/attribute/string it is
    built from — the ``path + ".tmp"`` idiom), and (b) no
    ``os.replace``/``os.rename`` call appears in the same function
    scope (which would promote the temp to final atomically). Append
    mode is out of scope: logs are torn-tail-truncated at recovery, not
    atomically replaced."""
    if ctx.tree is None or not ({"runtime", "serve"} & ctx.domains):
        return

    def names_temp(expr: ast.expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if "tmp" in node.value.lower():
                    return True
            elif isinstance(node, ast.Name) and "tmp" in node.id.lower():
                return True
            elif isinstance(node, ast.Attribute) and "tmp" in node.attr.lower():
                return True
        return False

    def write_mode(call: ast.Call) -> bool:
        mode = None
        if len(call.args) >= 2:
            mode = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = kw.value
        return (
            isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and mode.value in ("w", "wb")
        )

    scopes: list[list[ast.stmt]] = [ctx.tree.body]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node.body)
    for body in scopes:
        opens: list[ast.Call] = []
        has_replace = False
        for node in walk_excluding_nested_functions(body):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            if target in ("os.replace", "os.rename"):
                has_replace = True
            elif target == "open" and write_mode(node):
                if node.args and not names_temp(node.args[0]):
                    opens.append(node)
        if has_replace:
            continue
        for node in opens:
            yield ctx.finding(
                node,
                "ACT028",
                "state file opened 'w' on its final path with no "
                "os.replace/os.rename in scope: a crash mid-write leaves "
                "a torn file — write to a tmp sibling, fsync, then "
                "os.replace (runtime/persist.py discipline)",
            )


@rule("ACT013", "swallowed-cancellation", "CancelledError caught without re-raise")
def check_swallowed_cancel(ctx: FileContext):
    if ctx.tree is None:
        return
    # except BaseException / bare except inside an async def swallow
    # CancelledError just as thoroughly as naming it (CancelledError
    # derives from BaseException since 3.8) — but only flag them in
    # async execution scope, where a cancellation can actually arrive.
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in walk_excluding_nested_functions(fn.body):
            if not isinstance(node, ast.ExceptHandler):
                continue
            catches_everything = node.type is None or (
                ctx.resolve(node.type) == "BaseException"
            )
            if catches_everything and not _handler_reraises(node):
                yield ctx.finding(
                    node,
                    "ACT013",
                    ("bare except" if node.type is None else "except BaseException")
                    + " in async code swallows CancelledError too: the task "
                    "becomes unkillable (catch Exception, or re-raise)",
                )
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is not None:
            caught = (
                node.type.elts if isinstance(node.type, ast.Tuple) else [node.type]
            )
            if not any(_is_cancelled_error(ctx, c) for c in caught):
                continue
            if not _handler_reraises(node):
                yield ctx.finding(
                    node,
                    "ACT013",
                    "except CancelledError without re-raise: swallowing "
                    "cancellation makes the task unkillable (re-raise, or "
                    "suppress with a justification at a terminal point)",
                )
        elif isinstance(node, ast.Call):
            target = ctx.resolve(node.func)
            if target in ("contextlib.suppress", "suppress") and any(
                _is_cancelled_error(ctx, a)
                or ctx.resolve(a) == "BaseException"
                for a in node.args
            ):
                yield ctx.finding(
                    node,
                    "ACT013",
                    "suppress(CancelledError) swallows cancellation: the "
                    "awaiting task becomes unkillable (narrow the suppress, "
                    "or justify it at a terminal point)",
                )
