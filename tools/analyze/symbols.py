"""Whole-repo symbol graph: the collect pass of the two-phase engine.

The per-file tier (ACT00x-ACT04x) matches on naming conventions and a
single module's import map. The flow-sensitive ACT05x family needs more:
*which class* an attribute lives on, *which methods* share it, and what
*type* a ``self.*`` field was constructed with — so a lock is a lock
because ``__init__`` assigned ``asyncio.Lock()``, not because the name
contains "lock", and a pool is a pool because the resolved constructor
is a ``ConnectionPool``.

``SymbolGraph.build(contexts)`` consumes the same already-parsed
``FileContext`` objects the engine built (one parse per file stays
true); it never imports the code it audits.

Module naming: a file's dotted module name is derived from its real
package root — walk up while ``__init__.py`` exists — so
``aiocluster_tpu/runtime/pool.py`` is ``aiocluster_tpu.runtime.pool``
and a fixture package under ``tests/fixtures/analyze/`` gets its
natural short name (``symgraph_pkg.base``). Relative imports resolve
against that name; ``from x import y`` chains through re-exports to the
module that actually defines ``y``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .core import FileContext, dotted_name

#: Resolved constructor types treated as locks by ACT051 (async-with
#: discipline) — threading locks included: persist-style sync helpers
#: share classes with async callers.
LOCK_TYPES = frozenset({
    "asyncio.Lock",
    "asyncio.Condition",
    "asyncio.Semaphore",
    "asyncio.BoundedSemaphore",
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
})


@dataclass
class AttrInfo:
    """One ``self.<name>`` field of a class, aggregated over methods."""

    name: str
    type: str | None = None  # canonical dotted constructor, if inferable
    written_in_init: bool = False
    writer_methods: set[str] = field(default_factory=set)
    reader_methods: set[str] = field(default_factory=set)

    @property
    def methods(self) -> set[str]:
        return self.writer_methods | self.reader_methods

    @property
    def shared(self) -> bool:
        """Accessed by two or more methods — the precondition for an
        interleaving hazard (a single-method attr has no second party)."""
        return len(self.methods) >= 2


@dataclass
class ClassInfo:
    module: str
    qualname: str
    node: ast.ClassDef
    bases: tuple[str, ...] = ()
    methods: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = field(
        default_factory=dict
    )
    attrs: dict[str, AttrInfo] = field(default_factory=dict)

    @property
    def canonical(self) -> str:
        return f"{self.module}.{self.qualname}"

    def has_methods(self, *names: str) -> bool:
        return all(n in self.methods for n in names)


@dataclass
class ModuleInfo:
    name: str
    relpath: str
    package: str  # enclosing package ("" for a top-level module)
    imports: dict[str, str] = field(default_factory=dict)  # binding -> origin
    classes: dict[str, ClassInfo] = field(default_factory=dict)  # qualname ->
    defs: set[str] = field(default_factory=set)  # top-level defined names


def module_name_for(path: Path, relpath: str) -> tuple[str, str]:
    """(module name, enclosing package) for a file, from its real
    package root: walk up while ``__init__.py`` exists. Falls back to
    the dotted relpath when the file isn't on disk (unit-test strings).
    """
    parts: list[str]
    if path.name == "__init__.py":
        parts = []
        cur = path.parent
    else:
        parts = [path.stem]
        cur = path.parent
    try:
        on_disk = (cur / "__init__.py").exists()
    except OSError:
        on_disk = False
    if on_disk:
        while (cur / "__init__.py").exists() and cur.name:
            parts.insert(0, cur.name)
            cur = cur.parent
    else:
        rel = relpath[: -len(".py")] if relpath.endswith(".py") else relpath
        parts = rel.replace("\\", "/").split("/")
        if parts and parts[-1] == "__init__":
            parts.pop()
    name = ".".join(parts) if parts else path.stem
    if path.name == "__init__.py":
        return name, name  # a package IS its own import base
    pkg, _, _ = name.rpartition(".")
    return name, pkg


def _import_map(tree: ast.Module, package: str) -> dict[str, str]:
    """binding -> dotted origin, with relative imports resolved against
    the module's enclosing package (the piece core.build_import_map
    deliberately skips — it has no module identity to resolve against).
    """
    imap: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imap[a.asname] = a.name
                else:  # ``import a.b`` binds ``a`` (the package root)
                    root = a.name.partition(".")[0]
                    imap[root] = root
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                hops = package.split(".") if package else []
                if node.level - 1:
                    hops = hops[: -(node.level - 1)] if node.level - 1 <= len(hops) else []
                prefix = ".".join(hops)
                base = f"{prefix}.{node.module}" if node.module else prefix
            if not base:
                continue
            for a in node.names:
                if a.name != "*":
                    imap[a.asname or a.name] = f"{base}.{a.name}"
    return imap


def _collect_class(mod: ModuleInfo, node: ast.ClassDef, imap: dict[str, str]) -> ClassInfo:
    info = ClassInfo(
        module=mod.name,
        qualname=node.name,
        node=node,
        bases=tuple(filter(None, (dotted_name(b) for b in node.bases))),
    )
    for stmt in node.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info.methods[stmt.name] = stmt
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "self":
                attr = info.attrs.setdefault(sub.attr, AttrInfo(sub.attr))
                if isinstance(sub.ctx, ast.Store):
                    attr.writer_methods.add(stmt.name)
                    if stmt.name == "__init__":
                        attr.written_in_init = True
                else:
                    attr.reader_methods.add(stmt.name)
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self" \
                        and isinstance(sub.value, ast.Call):
                    ctor = dotted_name(sub.value.func)
                    if ctor:
                        attr = info.attrs.setdefault(tgt.attr, AttrInfo(tgt.attr))
                        if attr.type is None:
                            attr.type = ctor  # raw; canonicalized in pass 2
    return info


class SymbolGraph:
    """Modules, classes, and resolved names across one analyzed tree."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.by_relpath: dict[str, ModuleInfo] = {}
        self.classes: dict[str, ClassInfo] = {}

    @classmethod
    def build(cls, contexts: list[FileContext]) -> "SymbolGraph":
        g = cls()
        # Pass 1: module identity, imports, class/attr tables.
        for ctx in contexts:
            if ctx.tree is None:
                continue
            name, package = module_name_for(ctx.path, ctx.relpath)
            mod = ModuleInfo(name=name, relpath=ctx.relpath, package=package)
            mod.imports = _import_map(ctx.tree, package)
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mod.defs.add(stmt.name)
                elif isinstance(stmt, ast.ClassDef):
                    mod.defs.add(stmt.name)
                    mod.classes[stmt.name] = _collect_class(mod, stmt, mod.imports)
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            mod.defs.add(t.id)
            g.modules[name] = mod
            g.by_relpath[ctx.relpath] = mod
        # Pass 2: canonical class index + attr constructor types resolved
        # through the (now complete) import graph.
        for mod in g.modules.values():
            for ci in mod.classes.values():
                g.classes[ci.canonical] = ci
        for mod in g.modules.values():
            for ci in mod.classes.values():
                for attr in ci.attrs.values():
                    if attr.type:
                        attr.type = g.resolve(mod.name, attr.type)
        return g

    def resolve(self, module: str, dotted: str) -> str:
        """Canonical dotted origin of ``dotted`` as seen from ``module``:
        chase the module's import map, then re-export chains, until the
        name lands in the module that defines it (or leaves the graph).
        """
        seen: set[tuple[str, str]] = set()
        cur_mod, cur = module, dotted
        while (cur_mod, cur) not in seen:
            seen.add((cur_mod, cur))
            if not cur:
                return cur_mod  # the name IS a module (``from . import base``)
            mod = self.modules.get(cur_mod)
            if mod is None:
                return cur
            root, _, rest = cur.partition(".")
            if root in mod.defs and root not in mod.imports:
                return f"{mod.name}.{cur}"
            origin = mod.imports.get(root)
            if origin is None:
                return cur
            cur = f"{origin}.{rest}" if rest else origin
            # Re-enter from the module that (transitively) exports it:
            # the longest known-module prefix of the new dotted path.
            cur_mod, cur = self._split_known(cur)
        return cur

    def _split_known(self, dotted: str) -> tuple[str, str]:
        """(module, remainder-within-module) for the longest known-module
        prefix; falls back to ("", dotted) when nothing matches."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:i])
            if prefix in self.modules:
                return prefix, ".".join(parts[i:])
        return "", dotted

    def class_info(self, canonical: str) -> ClassInfo | None:
        return self.classes.get(canonical)

    def attr_type(self, ci: ClassInfo, attr: str) -> str | None:
        a = ci.attrs.get(attr)
        return a.type if a else None
