"""ACT04x — observability / trace-event discipline.

The digital twin (docs/twin.md) replays recorded traces through a
dispatcher keyed on each record's literal event kind: ``twin_node`` and
``twin_round`` records drive replay, ``trace_header`` gates schema
compatibility, everything else is provenance. An event emitted under a
*computed* kind is invisible to that dispatcher — it lands in the file
but no consumer will ever route it — so every ``TraceWriter`` emit site
in the instrumented packages must name its kind as a string literal,
where grep and the docs' event catalogue (docs/observability.md) can
see it too.
"""

from __future__ import annotations

import ast

from .core import FileContext, dotted_name, rule

# Packages whose emit sites feed the replay dispatcher / the documented
# event catalogue. tests/benchmarks stay out of scope (they fabricate
# records on purpose).
_TRACE_DOMAINS = {"runtime", "sim", "obs", "twin", "serve", "faults"}


def _is_trace_receiver(node: ast.expr) -> bool:
    """True for receivers that are trace writers by naming convention:
    the final name segment contains ``trace`` (``self._trace``,
    ``self._twin_trace``, ``self.trace``, a local ``trace``/``tw`` does
    not count unless named so)."""
    d = dotted_name(node)
    if d is None:
        return False
    return "trace" in d.rsplit(".", 1)[-1].lower()


@rule(
    "ACT040",
    "dynamic-trace-event-kind",
    "trace event emitted under a non-literal kind",
)
def check_trace_event_literal(ctx: FileContext):
    if ctx.tree is None or not (_TRACE_DOMAINS & ctx.domains):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "emit"):
            continue
        if not _is_trace_receiver(func.value):
            continue
        # The kind may ride positionally or as the named ``event=``
        # parameter (TraceWriter.emit's signature) — either way it must
        # be a string literal.
        first = node.args[0] if node.args else None
        if first is None:
            for kw in node.keywords:
                if kw.arg == "event":
                    first = kw.value
                    break
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            continue
        receiver = dotted_name(func.value) or "<trace>"
        what = (
            "no kind at all"
            if first is None
            else "a computed kind"
        )
        yield ctx.finding(
            node,
            "ACT040",
            f"{receiver}.emit(...) passes {what} — trace event kinds "
            "must be string literals (a dynamic kind is invisible to "
            "the twin replay dispatcher and the docs' event catalogue)",
        )
