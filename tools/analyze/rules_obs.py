"""ACT04x — observability / trace-event discipline.

The digital twin (docs/twin.md) replays recorded traces through a
dispatcher keyed on each record's literal event kind: ``twin_node`` and
``twin_round`` records drive replay, ``trace_header`` gates schema
compatibility, everything else is provenance. An event emitted under a
*computed* kind is invisible to that dispatcher — it lands in the file
but no consumer will ever route it — so every ``TraceWriter`` emit site
in the instrumented packages must name its kind as a string literal,
where grep and the docs' event catalogue (docs/observability.md) can
see it too.

ACT043 guards the fleet-telemetry plane's reserved keyspace the same
way: ``__fleet:``-prefixed keys are the contract boundary between
application data and gossip-borne self-telemetry (obs/fleet.py), and
every consumer must import the constants rather than respell the
prefix — a drifted literal silently splits the keyspace.

ACT044 guards the clock seam (docs/virtual-time.md): timed behavior in
the clocked packages reads ``utils.clock``, never ``time.*`` /
``datetime.now`` / bare ``asyncio.sleep``, so one virtual loop
compresses every window together and seeded chaos replays
bit-identically.
"""

from __future__ import annotations

import ast
import re

from .core import REPO_ROOT, FileContext, dotted_name, rule

# Packages whose emit sites feed the replay dispatcher / the documented
# event catalogue. tests/benchmarks stay out of scope (they fabricate
# records on purpose).
_TRACE_DOMAINS = {"runtime", "sim", "obs", "twin", "serve", "faults"}

# Packages whose metric registrations feed the documented catalogue
# (docs/observability.md). Same scoping rationale as above: fixture and
# bench code fabricates families on purpose.
_METRIC_DOMAINS = _TRACE_DOMAINS | {"ops", "core"}

# The registry's family constructors (obs/registry.py).
_METRIC_REGISTRARS = {"counter", "gauge", "histogram"}

_METRIC_NAME_RE = re.compile(r"aiocluster_[a-z0-9_:]+")

_documented_cache: frozenset[str] | None = None


def _documented_metric_names() -> frozenset[str]:
    """Every ``aiocluster_*`` token appearing in docs/observability.md
    — the catalogue ACT041 gates registrations against. Read once per
    process (the docs file is the same for every analyzed file)."""
    global _documented_cache
    if _documented_cache is None:
        try:
            text = (REPO_ROOT / "docs" / "observability.md").read_text(
                encoding="utf-8"
            )
        except OSError:
            text = ""
        _documented_cache = frozenset(_METRIC_NAME_RE.findall(text))
    return _documented_cache


def _is_trace_receiver(node: ast.expr) -> bool:
    """True for receivers that are trace writers by naming convention:
    the final name segment contains ``trace`` (``self._trace``,
    ``self._twin_trace``, ``self.trace``, a local ``trace``/``tw`` does
    not count unless named so)."""
    d = dotted_name(node)
    if d is None:
        return False
    return "trace" in d.rsplit(".", 1)[-1].lower()


@rule(
    "ACT040",
    "dynamic-trace-event-kind",
    "trace event emitted under a non-literal kind",
)
def check_trace_event_literal(ctx: FileContext):
    if ctx.tree is None or not (_TRACE_DOMAINS & ctx.domains):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "emit"):
            continue
        if not _is_trace_receiver(func.value):
            continue
        # The kind may ride positionally or as the named ``event=``
        # parameter (TraceWriter.emit's signature) — either way it must
        # be a string literal.
        first = node.args[0] if node.args else None
        if first is None:
            for kw in node.keywords:
                if kw.arg == "event":
                    first = kw.value
                    break
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            continue
        receiver = dotted_name(func.value) or "<trace>"
        what = (
            "no kind at all"
            if first is None
            else "a computed kind"
        )
        yield ctx.finding(
            node,
            "ACT040",
            f"{receiver}.emit(...) passes {what} — trace event kinds "
            "must be string literals (a dynamic kind is invisible to "
            "the twin replay dispatcher and the docs' event catalogue)",
        )


def _is_registry_receiver(node: ast.expr) -> bool:
    """Receivers that are metric registries by naming convention: the
    final name segment contains ``metrics`` or ``registry``
    (``self._metrics``, ``metrics``, ``self.registry``, ``registry``)."""
    d = dotted_name(node)
    if d is None:
        return False
    last = d.rsplit(".", 1)[-1].lower()
    return "metrics" in last or "registry" in last


@rule(
    "ACT041",
    "undocumented-metric-family",
    "metric family registered but absent from docs/observability.md",
)
def check_metric_documented(ctx: FileContext):
    """Docs-drift gate for the growing metric surface: every family
    name registered via ``registry.counter/gauge/histogram("...")`` in
    the instrumented packages must appear in docs/observability.md's
    catalogue tables — a metric an operator cannot look up is telemetry
    only its author can read. Only LITERAL names are checked (the one
    table-driven registration loop, obs/sim.py's ``_SAMPLE_GAUGES``,
    carries names the docs already list; a dynamic name cannot be
    verified here and is out of scope by design —
    docs/static-analysis.md)."""
    if ctx.tree is None or not (_METRIC_DOMAINS & ctx.domains):
        return
    documented = _documented_metric_names()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr in _METRIC_REGISTRARS
        ):
            continue
        if not _is_registry_receiver(func.value):
            continue
        first = node.args[0] if node.args else None
        if first is None:
            for kw in node.keywords:
                if kw.arg == "name":
                    first = kw.value
                    break
        if not (
            isinstance(first, ast.Constant) and isinstance(first.value, str)
        ):
            continue  # dynamic names are out of scope (docstring)
        name = first.value
        if not name.startswith("aiocluster_"):
            continue  # fixture/test families live outside the catalogue
        if name in documented:
            continue
        yield ctx.finding(
            node,
            "ACT041",
            f"metric family {name!r} is registered here but missing "
            "from docs/observability.md's catalogue — document it (the "
            "metric surface's docs-drift gate)",
        )


# The telemetry plane's reserved key prefix. Deliberately DUPLICATED
# from aiocluster_tpu/obs/fleet.py's TELEMETRY_PREFIX — the analyzer
# never imports the package it audits — and pinned equal to the real
# constant by tests/test_analyze.py so the two cannot drift apart.
_TELEMETRY_PREFIX = "__fleet:"

# The defining module: the one place allowed to spell the prefix.
_TELEMETRY_HOME = "obs/fleet.py"

# Packages that handle keys near the telemetry plane (publish, view
# assembly, serving). Everything else is out of scope: tests and
# benchmarks fabricate reserved keys on purpose.
_FLEET_DOMAINS = {"runtime", "serve", "obs"}


@rule(
    "ACT043",
    "reserved-telemetry-prefix-literal",
    "reserved __fleet: key prefix respelled as a literal",
)
def check_reserved_prefix_literal(ctx: FileContext):
    """Single-source gate for the reserved telemetry keyspace: any
    string literal beginning with ``__fleet:`` outside obs/fleet.py
    must instead import ``TELEMETRY_PREFIX``/``TELEMETRY_KEY`` — a
    respelled prefix is invisible to refactors of the constant and
    silently splits the keyspace (docs/static-analysis.md)."""
    if ctx.tree is None or not (_FLEET_DOMAINS & ctx.domains):
        return
    if ctx.relpath.endswith(_TELEMETRY_HOME):
        return
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Constant) and isinstance(node.value, str)
        ):
            continue
        if not node.value.startswith(_TELEMETRY_PREFIX):
            continue
        yield ctx.finding(
            node,
            "ACT043",
            f"string literal {node.value!r} respells the reserved "
            "telemetry key prefix — import TELEMETRY_PREFIX/"
            "TELEMETRY_KEY from aiocluster_tpu.obs.fleet instead (the "
            "reserved keyspace has one defining module)",
        )


# -- ACT044: the clock seam (docs/virtual-time.md) ---------------------------

# Packages whose time reads must flow through the utils.clock seam so a
# virtual loop compresses ALL of them together: one raw read is one
# subsystem whose windows silently stay on real time under a vtime soak
# (phi watches a frozen wall; TTLs never expire; replay diverges).
_CLOCK_DOMAINS = {"runtime", "serve", "faults", "core"}

# Raw clock reads / blocking sleeps banned in the clocked packages.
# datetime.date is date.today's origin under ``from datetime import date``.
_RAW_CLOCK_CALLS = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.sleep",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

# The sanctioned replacements, named in the finding message.
_CLOCK_SEAM_HINT = (
    "route it through the clock seam (aiocluster_tpu.utils.clock: "
    "resolve_clock/current_clock for reads, utc_now for datetimes, "
    "utils.clock.sleep for suspension) so virtual time compresses it "
    "(docs/virtual-time.md)"
)


def _is_literal_zero(node: ast.expr | None) -> bool:
    """The ``await asyncio.sleep(0)`` yield idiom — a scheduling point,
    not a timed wait; virtual time has nothing to compress there."""
    return (
        isinstance(node, ast.Constant)
        and type(node.value) in (int, float)
        and node.value == 0
    )


@rule(
    "ACT044",
    "raw-clock-or-sleep",
    "raw clock read or asyncio.sleep outside the clock seam",
)
def check_raw_clock_or_sleep(ctx: FileContext):
    """The virtual-time contract (docs/virtual-time.md): every timed
    behavior in the clocked packages — phi windows, breaker backoff,
    TTLs, fault windows, idle eviction, trace stamps — reads the ONE
    Clock seam, so ``vtime.VirtualClockLoop`` compresses them together
    and a seeded chaos soak replays bit-identically. A raw
    ``time.monotonic()``/``time.time()``/``datetime.now()`` read or a
    direct ``asyncio.sleep(dt)`` reintroduces real time into exactly
    one subsystem, which then drifts against the compressed rest —
    the kind of bug only a week-long soak exposes. ``asyncio.sleep(0)``
    (the yield idiom) is exempt; deliberate wall reads justify
    themselves with ``# noqa: ACT044 -- why`` (core/identity.py's
    generation stamp — wall-clock BY CONTRACT across restarts — is the
    template)."""
    if ctx.tree is None or not (_CLOCK_DOMAINS & ctx.domains):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        origin = ctx.resolve(node.func)
        if origin in _RAW_CLOCK_CALLS:
            yield ctx.finding(
                node,
                "ACT044",
                f"raw clock call {origin}() in a clocked package — "
                + _CLOCK_SEAM_HINT,
            )
        elif origin == "asyncio.sleep":
            first = node.args[0] if node.args else None
            if _is_literal_zero(first):
                continue
            yield ctx.finding(
                node,
                "ACT044",
                "asyncio.sleep(...) with a nonzero delay in a clocked "
                "package — " + _CLOCK_SEAM_HINT.replace(
                    "route it", "route the wait"
                ),
            )
