"""Analysis core: findings, the rule registry, and per-file context.

Every file is read and parsed exactly ONCE (``FileContext``); all rules —
including the migrated ACT00x style family that used to live in
tools/lint.py — consume the same AST. Rules register through the
``@rule`` decorator with a stable code; codes are the suppression and
baseline currency, so they must never be renumbered (retire a code
rather than reuse it).

Code families (docs/static-analysis.md has the full catalogue):

- ACT00x  style/imports (the old tools/lint.py checks)
- ACT01x  async-safety (blocking calls, dropped tasks, swallowed cancels)
- ACT02x  JAX purity / tracer discipline (host syncs, impure jit bodies)
- ACT03x  owner-write invariant (the paper's "only the owner mutates
          its keyspace" rule)
- ACT04x  observability / trace-event discipline (literal event kinds —
          the twin replay dispatcher routes on them)
- ACT05x  flow-sensitive concurrency (await-interleaving races, on the
          whole-repo symbol graph + per-function CFGs; empty baseline)
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from collections.abc import Callable, Iterable
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]

# ``# noqa`` (blanket) or ``# noqa: ACT012[, ACT013]`` with an optional
# ``-- justification`` trailer (encouraged; see docs/static-analysis.md).
_NOQA_RE = re.compile(
    r"#\s*noqa(?::\s*(?P<codes>[A-Z][A-Z0-9]*(?:\s*,\s*[A-Z][A-Z0-9]*)*))?",
    re.IGNORECASE,
)
# Fixture-corpus files opt into a domain the path doesn't imply, e.g.
# ``# analyze-domain: sim`` (tests/fixtures/analyze/ uses this so
# path-scoped rules stay testable outside their real directories).
_DOMAIN_RE = re.compile(r"#\s*analyze-domain:\s*([a-z0-9_\-, ]+)", re.IGNORECASE)


@dataclass
class Finding:
    """One rule violation at a location. ``status`` is assigned by the
    engine: new | suppressed | baselined."""

    path: str
    line: int
    col: int
    code: str
    message: str
    status: str = "new"

    def fingerprint(self) -> tuple[str, str, str]:
        """Baseline identity: deliberately excludes line/col so findings
        survive unrelated edits above them (messages carry names, not
        line numbers, for the same reason)."""
        return (self.path, self.code, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable[["FileContext"], Iterable[Finding]]


RULES: dict[str, Rule] = {}


def rule(code: str, name: str, summary: str):
    """Register a rule. ``check(ctx)`` yields Findings; it must tolerate
    ``ctx.tree is None`` (syntax-error files) by yielding nothing."""

    def deco(fn):
        if code in RULES:
            raise ValueError(f"duplicate rule code {code}")
        RULES[code] = Rule(code, name, summary, fn)
        return fn

    return deco


@dataclass
class FileContext:
    path: Path
    relpath: str  # posix, repo-root-relative when under the repo
    src: str
    lines: list[str]
    tree: ast.Module | None
    syntax_error: SyntaxError | None
    suppressions: dict[int, set[str] | None]  # line -> codes (None=blanket)
    domains: set[str]
    import_map: dict[str, str]  # local binding -> dotted origin
    #: SymbolGraph attached by the two-phase engine (analyze_paths)
    #: after the collect pass; None means "analyze this file alone" —
    #: flow-sensitive rules then build a single-file graph on demand.
    symbols: object | None = None

    def finding(self, node: ast.AST | int, code: str, message: str) -> Finding:
        if isinstance(node, int):
            line, col = node, 0
        else:
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
        return Finding(self.relpath, line, col, code, message)

    def is_suppressed(self, f: Finding) -> bool:
        codes = self.suppressions.get(f.line, ...)
        if codes is ...:
            return False
        return codes is None or f.code in codes

    def resolve(self, node: ast.AST) -> str | None:
        """Dotted origin of a Name/Attribute chain through the module's
        imports: with ``from time import sleep``, ``sleep`` resolves to
        ``time.sleep``; with ``from jax import random``, ``random.bits``
        resolves to ``jax.random.bits`` (so the stdlib-``random`` purity
        rule can't misfire on jax.random)."""
        d = dotted_name(node)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        base = self.import_map.get(root, root)
        return f"{base}.{rest}" if rest else base


def dotted_name(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def build_import_map(tree: ast.Module) -> dict[str, str]:
    imap: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imap[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        imap[a.asname or a.name] = f"{node.module}.{a.name}"
    return imap


def _parse_suppressions(src: str) -> dict[int, set[str] | None]:
    supp: dict[int, set[str] | None] = {}

    def record(line: int, text: str) -> None:
        m = _NOQA_RE.search(text)
        if not m:
            return
        codes = m.group("codes")
        if codes is None:
            supp[line] = None  # blanket
        elif supp.get(line, set()) is not None:
            cur = supp.setdefault(line, set())
            assert cur is not None
            cur.update(c.strip().upper() for c in codes.split(","))

    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                record(tok.start[0], tok.string)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Un-tokenizable (e.g. a syntax-error fixture): naive line scan.
        for lineno, line in enumerate(src.splitlines(), 1):
            if "#" in line:
                record(lineno, line[line.index("#"):])
    return supp


def _compute_domains(relpath: str, src: str) -> set[str]:
    p = relpath.replace("\\", "/")
    domains: set[str] = set()
    if "/sim/" in p:
        domains.add("sim")
    if "/ops/" in p:
        domains.add("ops")
    if "/core/" in p:
        domains.add("core")
    if "/runtime/" in p:
        domains.add("runtime")
    if "/serve/" in p:
        domains.add("serve")
    if "/obs/" in p:
        domains.add("obs")
    if "/twin/" in p:
        domains.add("twin")
    if "/faults/" in p:
        domains.add("faults")
    if "/wire/" in p:
        domains.add("wire")
    if p.endswith("runtime/transport.py"):
        domains.add("transport")
    if p.endswith("core/kvstate.py"):
        domains.add("kvstate")
    if p.endswith("core/cluster_state.py"):
        domains.add("cluster-state")
    for m in _DOMAIN_RE.finditer(src):
        for d in m.group(1).split(","):
            domains.add(d.strip().lower())
    return domains


def load_context(path: Path, root: Path | None = None) -> FileContext:
    root = root or REPO_ROOT
    try:
        rel = path.resolve().relative_to(root).as_posix()
    except ValueError:
        rel = path.as_posix()
    src = path.read_text(encoding="utf-8")
    tree: ast.Module | None = None
    err: SyntaxError | None = None
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as exc:
        err = exc
    return FileContext(
        path=path,
        relpath=rel,
        src=src,
        lines=src.splitlines(),
        tree=tree,
        syntax_error=err,
        suppressions=_parse_suppressions(src),
        domains=_compute_domains(rel, src),
        import_map=build_import_map(tree) if tree is not None else {},
    )


# Shared AST helpers ---------------------------------------------------------

def walk_excluding_nested_functions(body: list[ast.stmt]):
    """Walk statements without descending into nested function/class
    defs — for rules whose scope is "directly in THIS function's
    execution" (a nested def's body runs elsewhere, possibly in a
    thread via asyncio.to_thread). Scope-boundary nodes (defs, classes,
    lambdas) ARE yielded — at any depth — so callers can recurse into
    them deliberately; their bodies are just never entered here."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
