"""ACT04x (cont.) — wire data-plane copy discipline.

The zero-copy gossip data plane (wire/segments.py, `Config.wire_fastpath`)
holds a structural promise: payloads are assembled as lists of cached
buffer refs and written scatter-gather — the only full-payload
materializations are the sanctioned assembly/codec helpers (which encode
each buffer ONCE) and the explicitly-documented decode-side cache-key
conversions. A stray ``bytes(...)``, ``b"".join`` or bytes-concat
``+=`` on the hot path silently reintroduces the per-peer-per-round
copies the fast path exists to remove — and nothing would fail, it
would just get slower. ACT042 makes that a gate instead of a hope.
"""

from __future__ import annotations

import ast

from .core import FileContext, rule

# Files in scope: the wire package and the socket transport — the
# byte-moving hot path. (faults/runtime.py's byzantine materialization
# is OUT of scope by design: rewriting is documented to force a join.)
_COPY_DOMAINS = {"wire", "transport"}

# Sanctioned assembly/codec helpers: materializing a buffer is their
# JOB, and each materialization happens once per logical value (encode
# memoization / segment cache above them dedups the rest). Anything
# else in the domain that copies must either move into one of these or
# carry an explicit ``# noqa: ACT042 -- why`` justification.
_SANCTIONED_FUNCS = frozenset({
    # proto.py field/primitive emitters
    "_uvarint", "_field_str", "_field_msg", "_field_varint",
    "_field_varint_present",
    # proto.py message encoders (bytearray -> bytes materialization)
    "encode_kv_body", "encode_kv_update", "encode_node_id",
    "encode_node_digest", "_encode_digest_entry", "encode_node_delta",
    "encode_digest", "encode_delta", "encode_packet",
    "encode_trace_context",
    # native bulk marshaling (ctypes needs contiguous input)
    "encode_kv_updates", "decode_node_delta_raw",
    # framing
    "frame", "frame_header", "unframe",
    # segments.py assembly helpers
    "segment", "node_delta_parts", "cluster_id_field", "_len_prefixed",
    "syn_packet_parts", "synack_packet_parts", "ack_packet_parts",
})


def _is_bytes_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in {"bytes", "bytearray"}
    )


def _is_bytes_join(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "join"
        and isinstance(node.func.value, ast.Constant)
        and isinstance(node.func.value.value, bytes)
    )


def _is_bytes_augadd(node: ast.AST) -> bool:
    """``x += b"..."`` / ``x += bytes(...)`` — growing a buffer by
    concatenation (each step copies the whole accumulated payload)."""
    if not (isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add)):
        return False
    v = node.value
    return (
        (isinstance(v, ast.Constant) and isinstance(v.value, bytes))
        or _is_bytes_call(v)
    )


@rule(
    "ACT042",
    "hot-path-payload-copy",
    "payload materialization outside the sanctioned assembly helpers",
)
def check_hot_path_payload_copy(ctx: FileContext):
    """Flags ``bytes(...)``/``bytearray(...)`` calls, ``b"".join``, and
    bytes-concat ``+=`` in wire/ and runtime/transport.py outside the
    sanctioned assembly helpers (see _SANCTIONED_FUNCS) — the copy
    discipline the zero-copy data plane's throughput rests on
    (docs/static-analysis.md)."""
    if ctx.tree is None or not (_COPY_DOMAINS & ctx.domains):
        return
    # Walk with an enclosing-function stack so findings know whether
    # they sit inside a sanctioned helper.
    stack: list[tuple[ast.AST, bool]] = [(ctx.tree, False)]
    while stack:
        node, sanctioned = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sanctioned = sanctioned or node.name in _SANCTIONED_FUNCS
        hit = None
        if _is_bytes_call(node):
            hit = f"{node.func.id}(...) materializes a payload copy"
        elif _is_bytes_join(node):
            hit = 'b"".join(...) concatenates the whole payload'
        elif _is_bytes_augadd(node):
            hit = "bytes += concat re-copies the accumulated payload"
        if hit is not None and not sanctioned:
            yield ctx.finding(
                node,
                "ACT042",
                f"{hit} on the wire hot path — assemble through the "
                "sanctioned helpers (wire/segments.py, the proto "
                "encoders) or justify with a noqa (zero-copy "
                "data-plane discipline, docs/static-analysis.md)",
            )
        for child in ast.iter_child_nodes(node):
            stack.append((child, sanctioned))
