"""File iteration + rule execution + report assembly.

The engine walks the given paths (skipping the deliberate-violation
corpus under ``tests/fixtures/analyze/`` unless a file there is named
explicitly), builds one FileContext per file, runs every selected rule
over it, applies inline suppressions, then (optionally) the committed
baseline. tools/lint.py is a thin shim over this engine with
``select=("ACT00",)`` — one parser serves both gates.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path

from . import (  # noqa: ACT002 -- imported for rule registration side effects
    rules_async,
    rules_concurrency,
    rules_jax,
    rules_obs,
    rules_owner,
    rules_style,
    rules_wire,
)
from .core import RULES, FileContext, Finding, load_context
from .symbols import SymbolGraph

# Directory suffix of the deliberate-violation fixture corpus: analyzing
# it as part of the repo gate would (by design) light up every rule.
CORPUS_MARKER = "fixtures/analyze"

#: What the repo gate (`make analyze`, bench.py's health field, and the
#: acceptance command) analyzes.
DEFAULT_PATHS = ("aiocluster_tpu", "tests", "benchmarks", "tools",
                 "bench.py", "__graft_entry__.py")


@dataclass
class Report:
    files: int = 0
    findings: list[Finding] = field(default_factory=list)
    stale_baseline: int = 0

    def count(self, status: str) -> int:
        return sum(1 for f in self.findings if f.status == status)

    @property
    def new(self) -> int:
        return self.count("new")

    def by_code(self) -> dict[str, Counter]:
        out: dict[str, Counter] = {}
        for f in self.findings:
            out.setdefault(f.code, Counter())[f.status] += 1
        return out


def iter_py_files(paths: list[str | Path], *, include_corpus: bool = False):
    """Yield .py files. Directories recurse (sorted, corpus excluded);
    explicit file arguments are always analyzed."""
    seen: set[Path] = set()
    for p in paths:
        path = Path(p)
        if path.is_dir():
            for f in sorted(path.rglob("*.py")):
                if not include_corpus and CORPUS_MARKER in f.as_posix():
                    continue
                r = f.resolve()
                if r not in seen:
                    seen.add(r)
                    yield f
        elif path.suffix == ".py" and path.is_file():
            r = path.resolve()
            if r not in seen:
                seen.add(r)
                yield path
        else:
            raise FileNotFoundError(f"{path}: not a .py file or directory")


def selected_rules(select: tuple[str, ...] | None):
    if not select:
        return list(RULES.values())
    return [r for r in RULES.values() if any(r.code.startswith(s) for s in select)]


def analyze_file(ctx: FileContext, select: tuple[str, ...] | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for r in selected_rules(select):
        for f in r.check(ctx):
            if ctx.is_suppressed(f):
                f.status = "suppressed"
            findings.append(f)
    # Dedup (a rule re-visiting a shared subtree must not double-report),
    # then order for stable output.
    unique = {(f.path, f.line, f.col, f.code, f.message): f for f in findings}
    return sorted(
        unique.values(), key=lambda f: (f.path, f.line, f.col, f.code, f.message)
    )


def analyze_paths(
    paths: list[str | Path],
    *,
    select: tuple[str, ...] | None = None,
    include_corpus: bool = False,
    root: Path | None = None,
) -> Report:
    report = Report()
    # Phase 1 (collect): parse everything once and build the whole-repo
    # symbol graph, so the flow-sensitive rules resolve imports, class
    # attribute tables, and self.* field types across file boundaries.
    contexts: list[FileContext] = []
    for path in iter_py_files(paths, include_corpus=include_corpus):
        contexts.append(load_context(path, root=root))
    graph = SymbolGraph.build(contexts)
    # Phase 2 (analyze): run the selected rules over the same parses.
    for ctx in contexts:
        ctx.symbols = graph
        report.files += 1
        report.findings.extend(analyze_file(ctx, select))
    return report


def run_default(repo_root: Path | None = None) -> Report:
    """The repo gate, programmatically (bench.py's analyze_clean field
    and the self-check test): default paths + committed baseline."""
    from . import baseline as bl
    from .core import REPO_ROOT

    root = repo_root or REPO_ROOT
    report = analyze_paths([root / p for p in DEFAULT_PATHS], root=root)
    report.stale_baseline = bl.apply(report.findings, bl.load(bl.DEFAULT_BASELINE))
    return report
