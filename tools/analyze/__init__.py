"""Dependency-free, AST-based static analysis for this repo.

Five rule families over one shared parse per file (docs/static-analysis.md):

- ACT00x  style/imports (the old tools/lint.py, now a shim over this)
- ACT01x  async-safety for the runtime backend's event loop
- ACT02x  JAX purity / tracer discipline for the sim backend
- ACT03x  the paper's owner-write invariant around core/kvstate.py
- ACT05x  flow-sensitive concurrency: await-interleaving races detected
          on per-function CFGs over a whole-repo symbol graph

The engine is two-phase: a collect pass parses every file once and
builds the symbol graph (tools/analyze/symbols.py); the analyze pass
runs the rules over the same parses with the graph attached.

Inline suppression: ``# noqa: ACT012 -- justification``. Pre-existing
findings are grandfathered in tools/analyze/baseline.json; only NEW
findings fail the gate (`make analyze`, folded into `make check`).
"""

from .core import RULES, FileContext, Finding, Rule, rule
from .engine import (
    DEFAULT_PATHS,
    Report,
    analyze_file,
    analyze_paths,
    run_default,
)

__all__ = [
    "RULES",
    "FileContext",
    "Finding",
    "Rule",
    "rule",
    "DEFAULT_PATHS",
    "Report",
    "analyze_file",
    "analyze_paths",
    "run_default",
]
