"""Per-function control-flow graphs with await/suspension tracking.

The ACT05x family reasons about *paths*: a read that an ``await``
separates from the write consuming it, an acquired connection reaching
a ``return`` unsettled, a decrement that a jump skips. ``build_cfg``
lowers one function body to basic blocks of ordered **events**:

- ``("stmt", node)``            — a statement begins here (rules that
  classify whole statements — acquire/settle — scan these)
- ``("await", node)``           — a suspension point: ``await``, async
  ``for``/``with`` protocol steps, or a ``yield`` in an async generator
- ``("self_read", attr, node)`` — ``self.<attr>`` evaluated (Load)
- ``("self_write", attr, node)``— ``self.<attr>`` rebound (Store)
- ``("self_rw", attr, node)``   — ``self.<attr> += ...`` (atomic
  read-modify-write of the binding; never a stale-read hazard per se)

Within one statement events are ordered reads → awaits → writes, which
matches evaluation order for every assignment shape we care about
(``self.x = await f(self.y)``) and — crucially — makes a same-statement
re-read (``x, self.t = self.t, None``) register as *fresh* at its own
write.

``finally`` bodies are **duplicated** along every path that runs them —
normal completion, the exception edge, and each ``return``/``break``/
``continue`` that jumps through them — so a settle-in-finally covers
every exit the way the runtime actually executes it. Exception edges
are block-granular: any block of a ``try`` body may hand off to each
handler. Nested ``def``/``class``/``lambda`` bodies are opaque (they
run elsewhere, possibly on another thread).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

Event = tuple  # (kind, *payload, node)


@dataclass
class Block:
    id: int
    events: list[Event] = field(default_factory=list)
    succs: list[int] = field(default_factory=list)


@dataclass
class CFG:
    func: ast.FunctionDef | ast.AsyncFunctionDef
    blocks: list[Block]
    entry: int = 0
    exit: int = 1

    def iter_events(self):
        for b in self.blocks:
            for ev in b.events:
                yield b, ev


_SCOPE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _walk_expr(node: ast.AST):
    """Expression walk that never enters nested scopes (their bodies do
    not execute at this point in the flow)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _SCOPE):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _is_self_attr(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


class _Builder:
    def __init__(self, func: ast.FunctionDef | ast.AsyncFunctionDef):
        self.func = func
        self.blocks: list[Block] = []
        self.cur = self._new()   # entry = 0
        self.exit = self._new()  # exit = 1
        self.cur = self.blocks[0]
        # (continue_target, break_target, finally_depth at loop entry)
        self.loops: list[tuple[Block, Block, int]] = []
        self.finallies: list[list[ast.stmt]] = []

    # -- graph plumbing ----------------------------------------------------
    def _new(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def _edge(self, a: Block, b: Block) -> None:
        if b.id not in a.succs:
            a.succs.append(b.id)

    def _start(self, *preds: Block) -> Block:
        b = self._new()
        for p in preds:
            self._edge(p, b)
        return b

    # -- event extraction --------------------------------------------------
    def _events_for(self, stmt: ast.stmt, exprs: list[ast.AST]) -> None:
        """Emit ("stmt", …) then reads → awaits → writes for the given
        expression roots of one statement."""
        ev = self.cur.events
        ev.append(("stmt", stmt))
        reads: list[Event] = []
        awaits: list[Event] = []
        writes: list[Event] = []
        for root in exprs:
            for n in _walk_expr(root):
                if _is_self_attr(n):
                    if isinstance(n.ctx, ast.Store):
                        writes.append(("self_write", n.attr, n))
                    elif isinstance(n.ctx, ast.Load):
                        reads.append(("self_read", n.attr, n))
                elif isinstance(n, (ast.Await, ast.Yield, ast.YieldFrom)):
                    awaits.append(("await", n))
        if isinstance(stmt, ast.AugAssign) and _is_self_attr(stmt.target):
            # the binding-level RMW is atomic: drop the separate
            # read/write halves so it can't read as a stale-read pair
            writes = [("self_rw", stmt.target.attr, stmt.target)]
        ev.extend(reads)
        ev.extend(awaits)
        ev.extend(writes)

    # -- statement dispatch ------------------------------------------------
    def emit(self, stmts: list[ast.stmt]) -> None:
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            # decorators/defaults evaluate here; bodies do not
            self._events_for(s, list(s.decorator_list))
        elif isinstance(s, ast.If):
            self._if(s)
        elif isinstance(s, ast.While):
            self._loop(s, header_exprs=[s.test])
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self._loop(s, header_exprs=[s.iter, s.target],
                       header_await=isinstance(s, ast.AsyncFor))
        elif isinstance(s, ast.Try):
            self._try(s)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            self._with(s)
        elif isinstance(s, ast.Match):
            self._match(s)
        elif isinstance(s, ast.Return):
            self._events_for(s, [s.value] if s.value else [])
            self._run_finallies(0)
            self._edge(self.cur, self.exit)
            self.cur = self._new()  # unreachable continuation
        elif isinstance(s, (ast.Break, ast.Continue)):
            self._events_for(s, [])
            if self.loops:
                cont, brk, depth = self.loops[-1]
                self._run_finallies(depth)
                self._edge(self.cur, brk if isinstance(s, ast.Break) else cont)
            self.cur = self._new()
        elif isinstance(s, ast.Raise):
            self._events_for(s, [x for x in (s.exc, s.cause) if x])
            self._run_finallies(0)
            self._edge(self.cur, self.exit)
            self.cur = self._new()
        else:
            # simple statement: Assign/AnnAssign/AugAssign/Expr/Assert/
            # Delete/Global/Nonlocal/Pass/Import...
            self._events_for(s, [s])

    def _if(self, s: ast.If) -> None:
        self._events_for(s, [s.test])
        cond = self.cur
        then = self._start(cond)
        self.cur = then
        self.emit(s.body)
        then_exit = self.cur
        if s.orelse:
            els = self._start(cond)
            self.cur = els
            self.emit(s.orelse)
            after = self._start(then_exit, self.cur)
        else:
            after = self._start(cond, then_exit)
        self.cur = after

    def _loop(self, s, *, header_exprs: list, header_await: bool = False) -> None:
        header = self._start(self.cur)
        self.cur = header
        self._events_for(s, [e for e in header_exprs if e is not None])
        if header_await:
            header.events.append(("await", s))
        after = self._new()
        body = self._start(header)
        self.loops.append((header, after, len(self.finallies)))
        self.cur = body
        self.emit(s.body)
        self._edge(self.cur, header)  # back edge
        self.loops.pop()
        self.cur = self._start(header)
        if getattr(s, "orelse", None):
            self.emit(s.orelse)
        self._edge(self.cur, after)
        self.cur = after

    def _with(self, s) -> None:
        self._events_for(s, [it.context_expr for it in s.items]
                         + [it.optional_vars for it in s.items if it.optional_vars])
        if isinstance(s, ast.AsyncWith):
            self.cur.events.append(("await", s))  # __aenter__
        self.emit(s.body)
        if isinstance(s, ast.AsyncWith):
            self.cur.events.append(("await", s))  # __aexit__

    def _match(self, s: ast.Match) -> None:
        self._events_for(s, [s.subject])
        subj = self.cur
        exits = [subj]  # no-case-matches fall-through
        for case in s.cases:
            self.cur = self._start(subj)
            if case.guard is not None:
                self._events_for(case, [case.guard])
            self.emit(case.body)
            exits.append(self.cur)
        self.cur = self._start(*exits)

    def _try(self, s: ast.Try) -> None:
        self._events_for(s, [])
        if s.finalbody:
            self.finallies.append(s.finalbody)
        body_first = len(self.blocks)
        body_entry = self._start(self.cur)
        self.cur = body_entry
        self.emit(s.body)
        body_exit = self.cur
        body_blocks = self.blocks[body_first:]
        if s.orelse:
            self.emit(s.orelse)
            body_exit = self.cur
        normal_exits = [body_exit]
        for h in s.handlers:
            hb = self._new()
            for bb in body_blocks:  # an exception can arise in any body block
                self._edge(bb, hb)
            self.cur = hb
            self._events_for(h, [h.type] if h.type else [])
            self.emit(h.body)
            normal_exits.append(self.cur)
        if s.finalbody:
            self.finallies.pop()
            # exceptional run of the finally: propagates onward (exit),
            # through any outer finallies
            if not s.handlers:
                exc_fin = self._new()
                for bb in body_blocks:
                    self._edge(bb, exc_fin)
                save = self.cur
                self.cur = exc_fin
                self._emit_finally(s.finalbody)
                self._run_finallies(0)
                self._edge(self.cur, self.exit)
                self.cur = save
            # normal run: falls through to the continuation
            self.cur = self._start(*normal_exits)
            self._emit_finally(s.finalbody)
        else:
            self.cur = self._start(*normal_exits)

    # -- finally duplication ----------------------------------------------
    def _emit_finally(self, finalbody: list[ast.stmt]) -> None:
        """Inline one finally body at the current point. The enclosing
        finally stack is trimmed so a jump *inside* the finally doesn't
        re-run it."""
        try:
            idx = next(i for i, fb in enumerate(self.finallies) if fb is finalbody)
            saved = self.finallies
            self.finallies = self.finallies[:idx]
        except StopIteration:
            saved = None
        self.emit(finalbody)
        if saved is not None:
            self.finallies = saved

    def _run_finallies(self, down_to: int) -> None:
        """Inline every pending finally body above ``down_to``
        (innermost first) — the path a jump statement actually takes."""
        for fb in reversed(self.finallies[down_to:]):
            saved = self.finallies
            self.finallies = self.finallies[: saved.index(fb)]
            self.emit(fb)
            self.finallies = saved


def build_cfg(func: ast.FunctionDef | ast.AsyncFunctionDef) -> CFG:
    b = _Builder(func)
    b.emit(func.body)
    b._edge(b.cur, b.exit)
    return CFG(func=func, blocks=b.blocks)


# -- dataflow helpers used by rules_concurrency ------------------------------

def dataflow(cfg: CFG, init, transfer, merge):
    """Generic forward fixpoint: ``transfer(state, block) -> state``,
    ``merge(a, b) -> a∪b``. Returns block-entry states."""
    states = {cfg.entry: init}
    work = [cfg.entry]
    while work:
        bid = work.pop()
        out = transfer(dict(states[bid]), cfg.blocks[bid])
        for succ in cfg.blocks[bid].succs:
            if succ in states:
                merged = merge(states[succ], out)
                if merged != states[succ]:
                    states[succ] = merged
                    work.append(succ)
            else:
                states[succ] = dict(out)
                work.append(succ)
    return states
