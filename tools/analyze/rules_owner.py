"""ACT03x — the paper's owner-write invariant.

ScuttleButt's core correctness rule: only the OWNER mutates its
keyspace; replicas converge exclusively through the version-ordered
delta-apply path (core/kvstate.py::apply_delta). A direct write to a
peer's NodeState from anywhere else forks version history — the peer
will keep gossiping versions the owner never issued, and the CRDT join
can never reconcile them. These rules fence that path syntactically.
"""

from __future__ import annotations

import ast

from .core import FileContext, rule

# NodeState version-structure fields only the owner (or the delta path)
# may assign.
PROTECTED_FIELDS = {"heartbeat", "max_version", "last_gc_version", "key_values"}
# Owner-only mutators: calling one of these on a PEER's state forks its
# version history. (apply_delta/apply_heartbeat are the sanctioned
# replica-side operations and are deliberately absent.)
OWNER_MUTATORS = {
    "set",
    "set_versioned",
    "set_with_version",
    "set_with_ttl",
    "delete",
    "delete_after_ttl",
    "inc_heartbeat",
}
# Receiver shapes that denote "some peer's state" rather than our own:
# a _node_states[...] subscript or a node_state lookup in the call chain.
PEER_LOOKUPS = {"node_state", "node_state_or_default"}


def _exempt(ctx: FileContext) -> bool:
    # kvstate.py IS the invariant's implementation; cluster_state.py is
    # its container (delta routing, GC, removal).
    return bool({"kvstate", "cluster-state"} & ctx.domains)


def _mentions_peer_lookup(node: ast.expr) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "_node_states":
            return True
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in PEER_LOOKUPS
        ):
            return True
    return False


@rule("ACT030", "nodestate-field-write", "direct write to NodeState version fields")
def check_field_write(ctx: FileContext):
    if ctx.tree is None or _exempt(ctx):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            # Flatten tuple/list/starred unpacking so `peer.heartbeat, x
            # = 1, 2` can't slip through the fence.
            flat: list[ast.expr] = []
            stack = list(targets)
            while stack:
                t = stack.pop()
                if isinstance(t, (ast.Tuple, ast.List)):
                    stack.extend(t.elts)
                elif isinstance(t, ast.Starred):
                    stack.append(t.value)
                else:
                    flat.append(t)
            for t in flat:
                # X.key_values[...] = ... assigns through the subscript.
                base = t.value if isinstance(t, ast.Subscript) else t
                if not isinstance(base, ast.Attribute):
                    continue
                if base.attr not in PROTECTED_FIELDS:
                    continue
                if isinstance(base.value, ast.Name) and base.value.id == "self":
                    continue  # a class maintaining its own fields
                # Anchor on the target, not the statement: a swap writes
                # two protected fields on one line and must report both.
                yield ctx.finding(
                    base,
                    "ACT030",
                    f"direct write to NodeState.{base.attr} outside "
                    "core/kvstate.py: version structures may only change "
                    "through owner writes or apply_delta",
                )


@rule("ACT031", "peer-kv-mutation", "owner-only mutator called on a peer's state")
def check_peer_mutation(ctx: FileContext):
    if ctx.tree is None or _exempt(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in OWNER_MUTATORS:
            continue
        if _mentions_peer_lookup(node.func.value):
            yield ctx.finding(
                node,
                "ACT031",
                f"'{node.func.attr}()' on a peer NodeState: only the owner "
                "mutates its keyspace — replicas must go through "
                "apply_delta (core/kvstate.py)",
            )


@rule("ACT032", "private-state-access", "reach into ClusterState._node_states")
def check_private_access(ctx: FileContext):
    if ctx.tree is None or _exempt(ctx):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute) and node.attr == "_node_states":
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                continue  # the owning class itself
            yield ctx.finding(
                node,
                "ACT032",
                "access to ClusterState._node_states outside core/: use "
                "the public surface (node_state/node_states/digest) so the "
                "owner-write fence stays auditable",
            )
