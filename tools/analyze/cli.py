"""Command-line front end: text/JSON/SARIF output, baseline handling,
exit codes.

Exit codes: 0 = clean (every finding suppressed or baselined),
1 = new findings, 2 = usage error. The JSON schema is stable
(``aiocluster-analyze/1``), the SARIF output is 2.1.0 (for CI
annotation surfaces), and both are covered by tests/test_analyze.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import baseline as bl
from .core import RULES, Rule
from .engine import Report, analyze_paths, selected_rules

JSON_SCHEMA = "aiocluster-analyze/1"
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def known_families() -> dict[str, str]:
    """family label (e.g. ``ACT05x``) -> rule-code prefix."""
    return {f"{code[:5]}x": code[:5] for code in sorted(RULES)}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="Domain-aware static analysis (ACT00x style, ACT01x "
        "async-safety, ACT02x JAX purity, ACT03x owner-write invariant). "
        "See docs/static-analysis.md.",
    )
    p.add_argument("paths", nargs="*", help=".py files or directories")
    p.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    p.add_argument(
        "--baseline",
        type=Path,
        default=bl.DEFAULT_BASELINE,
        help="baseline file grandfathering pre-existing findings "
        "(default: tools/analyze/baseline.json)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding as new (ignore the baseline file)",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="snapshot current findings into the baseline file and exit 0 "
        "(REPLACES the file: run it over the full gate paths with every "
        "rule — it refuses to combine with --select)",
    )
    p.add_argument(
        "--select", default=None, metavar="PREFIX[,PREFIX]",
        help="only run rules whose code matches a prefix (e.g. ACT01,ACT02)",
    )
    p.add_argument(
        "--only-family", default=None, metavar="FAMILY",
        help="fast path for one rule family by its catalogue label "
        "(e.g. ACT05x); unknown families are a usage error (exit 2)",
    )
    p.add_argument(
        "--include-corpus", action="store_true",
        help="also analyze the deliberate-violation fixture corpus "
        "(tests/fixtures/analyze/, excluded by default)",
    )
    p.add_argument("--list-rules", action="store_true")
    return p


def report_json(report: Report, rules: list[Rule]) -> dict:
    counts = {
        s: report.count(s) for s in ("new", "baselined", "suppressed")
    }
    counts["total"] = len(report.findings)
    counts["stale_baseline"] = report.stale_baseline
    return {
        "schema": JSON_SCHEMA,
        "files": report.files,
        "rules": [
            {"code": r.code, "name": r.name, "summary": r.summary}
            for r in sorted(rules, key=lambda r: r.code)
        ],
        "counts": counts,
        "by_code": {
            code: dict(statuses) for code, statuses in sorted(report.by_code().items())
        },
        "findings": [
            {
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "code": f.code,
                "message": f.message,
                "status": f.status,
            }
            for f in report.findings
        ],
    }


def report_sarif(report: Report, rules: list[Rule]) -> dict:
    """SARIF 2.1.0 — the CI-annotation interchange shape. Suppressed and
    baselined findings are carried with a ``suppressions`` entry so the
    viewer shows them struck-through rather than losing them."""
    results = []
    for f in report.findings:
        res = {
            "ruleId": f.code,
            "level": "error" if f.status == "new" else "note",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": f.line,
                            "startColumn": max(1, f.col + 1),
                        },
                    }
                }
            ],
        }
        if f.status != "new":
            res["suppressions"] = [
                {
                    "kind": "inSource" if f.status == "suppressed" else "external",
                    "justification": f.status,
                }
            ]
        results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "aiocluster-analyze",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [
                            {
                                "id": r.code,
                                "name": r.name,
                                "shortDescription": {"text": r.summary},
                            }
                            for r in sorted(rules, key=lambda r: r.code)
                        ],
                    }
                },
                "results": results,
            }
        ],
    }


def report_text(report: Report, rules: list[Rule], out=sys.stdout, err=sys.stderr) -> None:
    for f in report.findings:
        if f.status == "new":
            print(f.render(), file=out)
    by_code = report.by_code()
    print(
        f"analyze: {report.files} files, {len(rules)} rules, "
        f"{len(report.findings)} finding(s): {report.count('new')} new, "
        f"{report.count('baselined')} baselined, "
        f"{report.count('suppressed')} suppressed"
        + (
            f", {report.stale_baseline} stale baseline entr"
            + ("y" if report.stale_baseline == 1 else "ies")
            if report.stale_baseline
            else ""
        ),
        file=err,
    )
    for r in sorted(rules, key=lambda r: r.code):
        statuses = by_code.get(r.code, {})
        total = sum(statuses.values())
        detail = (
            " ".join(f"{n} {s}" for s, n in sorted(statuses.items()))
            if total
            else "clean"
        )
        print(f"  {r.code} {r.name:<24} {detail}", file=err)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(RULES):
            r = RULES[code]
            print(f"{code}  {r.name:<24} {r.summary}")
        return 0
    if not args.paths:
        print("usage: python -m tools.analyze PATH...", file=sys.stderr)
        return 2
    select = tuple(s.strip() for s in args.select.split(",")) if args.select else None
    if args.only_family:
        if select:
            print(
                "analyze: --only-family and --select are two spellings of "
                "the same filter — pass one",
                file=sys.stderr,
            )
            return 2
        families = known_families()
        label = args.only_family.strip()
        prefix = families.get(label) or families.get(f"{label.upper()}")
        if prefix is None and label.upper() in families.values():
            prefix = label.upper()  # accept the bare prefix spelling too
        if prefix is None:
            print(
                f"analyze: unknown rule family {label!r} — known families: "
                + ", ".join(sorted(families))
                + " (see docs/static-analysis.md)",
                file=sys.stderr,
            )
            return 2
        select = (prefix,)
    if args.write_baseline and select:
        # A narrowed run would REPLACE the baseline with its subset,
        # silently un-grandfathering every other family's findings.
        print(
            "analyze: refusing --write-baseline with --select: the "
            "baseline is replaced whole, so a narrowed snapshot would "
            "drop every other rule family's entries",
            file=sys.stderr,
        )
        return 2
    try:
        report = analyze_paths(
            args.paths, select=select, include_corpus=args.include_corpus
        )
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if args.write_baseline:
        n = bl.write(args.baseline, report.findings)
        print(f"analyze: wrote {n} baseline entr{'y' if n == 1 else 'ies'} "
              f"to {args.baseline}", file=sys.stderr)
        return 0
    if not args.no_baseline and args.baseline.exists():
        try:
            baseline = bl.load(args.baseline)
        except (ValueError, KeyError, TypeError) as exc:
            # json.JSONDecodeError is a ValueError: one branch covers
            # malformed JSON, wrong schema, and missing fields.
            print(
                f"analyze: unreadable baseline {args.baseline}: {exc} "
                "(regenerate with --write-baseline)",
                file=sys.stderr,
            )
            return 2
        report.stale_baseline = bl.apply(report.findings, baseline)
    rules = selected_rules(select)
    if args.format == "json":
        print(json.dumps(report_json(report, rules), indent=1))
    elif args.format == "sarif":
        print(json.dumps(report_sarif(report, rules), indent=1))
    else:
        report_text(report, rules)
    return 1 if report.new else 0
