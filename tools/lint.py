#!/usr/bin/env python3
"""Dependency-free lint for this repo (the image ships no ruff/flake8).

Checks, per Python file:
- syntax errors (ast.parse)
- unused imports (module scope, aliasing-aware; ``__init__.py`` re-exports
  and explicit ``__all__`` members are exempt)
- duplicate imports of the same binding
- ``__all__`` entries that aren't defined at module scope
- tabs in indentation and trailing whitespace

Exit code 0 = clean, 1 = findings. Usage: python tools/lint.py PATH...
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path


def _iter_py(paths: list[str]):
    for p in paths:
        path = Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            if not path.is_file():
                print(f"{path}: no such file", file=sys.stderr)
                raise SystemExit(2)
            yield path


def _module_all(tree: ast.Module) -> list[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        value = ast.literal_eval(node.value)
                    except ValueError:
                        return []
                    return [str(v) for v in value]
    return []


def _top_level_names(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                names.add((alias.asname or alias.name).split(".")[0])
    return names


def check_file(path: Path) -> list[str]:
    problems: list[str] = []
    src = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]

    for lineno, line in enumerate(src.splitlines(), 1):
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            problems.append(f"{path}:{lineno}: trailing whitespace")
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            problems.append(f"{path}:{lineno}: tab in indentation")

    exported = set(_module_all(tree))
    is_package_init = path.name == "__init__.py"

    # Collect module-scope imports: binding -> first line.
    imports: dict[str, int] = {}
    seen_targets: set[str] = set()
    duplicate: list[tuple[str, int]] = []
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue  # future statements are directives, not bindings
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = (alias.asname or alias.name).split(".")[0]
                # Dedup on the full dotted target: `import a.b` and
                # `import a.c` both bind `a` but are not duplicates.
                target = alias.asname or alias.name
                if isinstance(node, ast.ImportFrom):
                    target = f"{node.module}:{target}"
                if target in seen_targets:
                    duplicate.append((bound, node.lineno))
                else:
                    seen_targets.add(target)
                    imports.setdefault(bound, node.lineno)
    for name, lineno in duplicate:
        problems.append(f"{path}:{lineno}: duplicate import of '{name}'")

    # Usage scan: every Name load + attribute roots + names in string
    # annotations are "uses"; so is appearing in __all__.
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # crude but effective: string annotations / docstring refs
            for token in node.value.replace(".", " ").split():
                used.add(token)
    for name, lineno in imports.items():
        if name in used or name in exported or is_package_init:
            continue
        problems.append(f"{path}:{lineno}: unused import '{name}'")

    if exported:
        defined = _top_level_names(tree)
        # PEP 562 lazy exports: a module __getattr__ may serve any name.
        has_module_getattr = any(
            isinstance(n, ast.FunctionDef) and n.name == "__getattr__"
            for n in tree.body
        )
        if not has_module_getattr:
            for name in exported:
                if name not in defined:
                    problems.append(
                        f"{path}:1: __all__ exports undefined name '{name}'"
                    )
    return problems


def main(argv: list[str]) -> int:
    if not argv:
        print("usage: python tools/lint.py PATH...", file=sys.stderr)
        return 2
    problems: list[str] = []
    n_files = 0
    for path in _iter_py(argv):
        n_files += 1
        problems.extend(check_file(path))
    for p in problems:
        print(p)
    print(
        f"lint: {n_files} files, {len(problems)} problem(s)",
        file=sys.stderr,
    )
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
