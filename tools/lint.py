#!/usr/bin/env python3
"""Style lint — thin shim over tools.analyze's ACT00x family.

The checks that used to live here (syntax errors, unused/duplicate
imports, __all__ hygiene, whitespace) are now rules ACT001-ACT006 in
``tools/analyze`` so one engine parses each file once for lint AND the
domain rules (async-safety, JAX purity, owner-write invariant). This
shim keeps the historical entry point and contract: exit 0 = clean,
1 = findings, 2 = usage error; no baseline — style findings are always
fixed, never grandfathered.

Migration fix shipped with the move: the old "usage" scan credited an
import whenever its name appeared in ANY string constant (docstrings
included), silently missing genuinely unused imports. ACT002 now
credits string mentions only in annotation contexts.

Usage: python tools/lint.py PATH...
"""

from __future__ import annotations

import sys
from pathlib import Path

# Runnable both as `python tools/lint.py` (script: repo root not on
# sys.path) and as `python -m tools.lint`.
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tools.analyze.cli import main as analyze_main  # noqa: E402


def main(argv: list[str]) -> int:
    if not argv:
        print("usage: python tools/lint.py PATH...", file=sys.stderr)
        return 2
    return analyze_main(["--select", "ACT00", "--no-baseline", *argv])


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
