"""Repo tooling: ``tools.lint`` (style shim) and ``tools.analyze``
(domain-aware static analysis — see docs/static-analysis.md)."""
