# Developer/CI entry points (reference parity: its Makefile ships
# test/cov/lint plus a proto regeneration target, Makefile:13-26).

PY ?= python
LINT_PATHS = aiocluster_tpu tests benchmarks tools bench.py __graft_entry__.py

.PHONY: test test-all lint analyze analyze-concurrency chaos atlas atlas-smoke sweep-bench kernel-parity multihost-smoke serve-bench serve-smoke overload-bench overload-smoke restart-bench restart-smoke vtime-bench vtime-smoke twin-bench twin-smoke prov-bench prov-smoke wire-bench wire-smoke fleet-bench fleet-smoke check cov protos smoke obs-demo clean

# Fast verification loop: everything except tests marked `slow`
# (interpret-mode Pallas sweeps, multi-device mesh sims, subprocess
# suites — minutes each on a 1-core CPU host). Target: < 2 minutes.
test:
	$(PY) -m pytest tests/ -q -m "not slow"

# The whole suite, slow kernels included (what CI/judging should run).
test-all:
	$(PY) -m pytest tests/ -q

lint:
	$(PY) tools/lint.py $(LINT_PATHS)

# Domain-aware static analysis (docs/static-analysis.md): async-safety,
# JAX purity, and the paper's owner-write invariant, plus the ACT00x
# style family and the flow-sensitive ACT05x await-interleaving tier.
# The baseline (tools/analyze/baseline.json) is EMPTY — every finding
# is either fixed or justify-suppressed in source; any NEW finding fails.
analyze:
	$(PY) -m tools.analyze $(LINT_PATHS)

# Fast iteration loop for concurrency work: only the flow-sensitive
# ACT05x family (CFG + whole-repo symbol graph), skipping the syntactic
# tiers. Same paths and exit semantics as `analyze`.
analyze-concurrency:
	$(PY) -m tools.analyze --only-family ACT05x $(LINT_PATHS)

# Deterministic chaos soak (docs/faults.md): seeded flaky_links +
# split_brain + crash/restart against real loopback fleets and the sim,
# < 60 s on a 1-core host — the fast standalone loop for fault work.
# The soak is part of the tests/ tree, so `check` runs it via test-all
# (full-scale variants included); listing `chaos` as a separate
# prerequisite would run the same tests twice per CI pass.
chaos:
	$(PY) -m pytest tests/test_chaos.py -q -m "not slow"

# Byzantine tolerance atlas (benchmarks/byzantine_bench.py,
# docs/faults.md "byzantine"): the (byz fraction x phi_threshold x
# fanout) phase map as sweep lanes under ONE compile, written to
# build/atlas.json — convergence/false-positive phase boundaries per
# detector operating point. Full grid ~36 lanes at 512 nodes (CPU, a
# few minutes); the smoke grid (3x3 sheet, 128 nodes, ~30 s) gates CI.
atlas:
	mkdir -p build
	JAX_PLATFORMS=cpu $(PY) benchmarks/byzantine_bench.py --out build/atlas.json

atlas-smoke:
	mkdir -p build
	JAX_PLATFORMS=cpu $(PY) benchmarks/byzantine_bench.py --smoke --out build/atlas.json

# Sweep-engine smoke (benchmarks/sweep_bench.py): an 8-lane vmapped
# sweep must finish the same scenarios in < 0.5x the wall time of 8
# sequential runs (compile amortization), with per-lane
# rounds-to-convergence parity. CPU, small N, ~30 s.
sweep-bench:
	JAX_PLATFORMS=cpu $(PY) benchmarks/sweep_bench.py --smoke

# Fused-round kernel parity (docs/sim.md): the one-pass pull+FD kernel
# — slow interpret-mode differential tests included — must stay
# bit-identical to the XLA path for lean/full/dead-grace/fault-masked
# and multi-lane sweep configs, unsharded and under a 2-shard mesh.
# This is the merge gate for kernel work when the accelerator is
# unreachable; the compiled path is certified on-chip by bench.py.
kernel-parity:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fused_kernel.py -q

# Serve tier (benchmarks/serve_bench.py, docs/serving.md): epoch-cached
# snapshot fan-out against a real loopback fleet. Full scale drives
# 10k+ child-process long-poll watchers at 64 nodes and GATES on
# measured encode-once (exactly one payload encode per epoch bump) and
# a >= 10x cached-vs-per-request-encode reader ratio; the smoke variant
# (64 watchers, 8 nodes, >= 2x floor) gates CI via `check`.
serve-bench:
	$(PY) benchmarks/serve_bench.py

serve-smoke:
	$(PY) benchmarks/serve_bench.py --smoke

# Overload & degradation (benchmarks/overload_bench.py,
# docs/robustness.md): a slow-peer storm (adaptive timeouts + circuit
# breakers) plus a reader surge against serve-tier admission control,
# layer ON vs OFF on real loopback fleets. GATES on shedding-arm
# availability >= 2x the no-layer control at the same load, monotone
# serve epochs through the storm, at least one breaker opened, and the
# adaptive-p99 datum present. ~1 min on a 2-core host.
overload-bench:
	$(PY) benchmarks/overload_bench.py

overload-smoke:
	$(PY) benchmarks/overload_bench.py --smoke

# Durable node state (benchmarks/restart_bench.py, docs/robustness.md
# "Durability & lifecycle"): a rolling restart run warm (persistence on,
# graceful close, store-restored rejoin) vs cold (the reference's
# amnesiac reboot) on real loopback fleets. GATES: warm re-replication
# bytes <= 0.1x cold AND strictly faster reconvergence, plus graceful
# leave detected by peers faster than the measured phi window. The
# smoke (4 nodes, ~5 s) gates CI via `check`.
restart-bench:
	$(PY) benchmarks/restart_bench.py

restart-smoke:
	$(PY) benchmarks/restart_bench.py --smoke

# Virtual-time runtime (benchmarks/vtime_bench.py, docs/virtual-time.md):
# a real loopback fleet on the compressed clock. Full scale drives 200
# protocol instances through a virtual HOUR and GATES on <= 120 s wall
# (>= 30x compression), bit-identical seeded chaos replay, and the
# long-horizon scenario pack (dead-node GC lifecycle, week-long drift,
# slow-leak churn). The smoke (16 nodes, ten virtual minutes, < 10 s
# wall) gates CI via `check`.
vtime-bench:
	$(PY) benchmarks/vtime_bench.py

vtime-smoke:
	$(PY) benchmarks/vtime_bench.py --smoke

# Digital twin closed loop (benchmarks/twin_bench.py, docs/twin.md):
# record a twin-grade trace from a real loopback fleet, replay it
# through the deterministic sim, fit the runtime<->sim transfer on the
# first half and validate it on the HELD-OUT second half, then drive
# the SLO autotuner over a candidate grid under ONE sweep compile.
# GATES: held-out prediction within the stated tolerance, exactly one
# jit compile for the whole grid, and the recommended config's
# predicted convergence strictly beating the default config's. The
# smoke (6 nodes, 8 lanes, ~30 s CPU) gates CI via `check`.
twin-bench:
	JAX_PLATFORMS=cpu $(PY) benchmarks/twin_bench.py

twin-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/twin_bench.py --smoke

# Propagation provenance (benchmarks/propagation_bench.py,
# docs/observability.md "Propagation & provenance"): one marked write
# on a real loopback fleet — GATES: the provenance join covers >= 99%
# of the fleet's applies, the measured write→99%-visibility latency +
# hop-depth p99 + the sim's wavefront prediction are all present, and
# the sim staleness tensor bit-matches a host oracle on the int32 AND
# u4r rungs, unsharded + 2-shard. The smoke (8 nodes, ~1 min CPU)
# gates CI via `check`.
prov-bench:
	$(PY) benchmarks/propagation_bench.py

prov-smoke:
	$(PY) benchmarks/propagation_bench.py --smoke

# Zero-copy wire data plane (benchmarks/handshake_bench.py,
# docs/migration.md difference #16): quiescent + write-heavy handshake
# storms, wire_fastpath ON vs OFF on the same pooled fleets. GATES:
# fast >= 1.5x control handshakes/s quiescent, write-arm encode calls
# per handshake strictly below control (the segment-cache collapse),
# and at least one segment/shared-payload cache hit (engagement).
# Frame byte-identity vs the oracle codec is pinned separately by
# tests/test_wire_fastpath.py. Smoke ~15 s on a 1-core host.
wire-bench:
	$(PY) benchmarks/handshake_bench.py --gate

wire-smoke:
	$(PY) benchmarks/handshake_bench.py --smoke --gate

# Fleet telemetry plane (benchmarks/fleet_bench.py,
# docs/observability.md "Fleet telemetry"): gossip-borne health digests
# + any-member fleet views through a split-brain heal, with wire-level
# trace context on. GATES: a random member's view covers >= 99% of the
# fleet with bounded staleness p99, per-entry advertised watermarks
# stay monotone across the heal, and the marked write's provenance
# joins 100% of applies EXACTLY (zero send-heuristic joins). The smoke
# (6 nodes, ~20 s CPU) gates CI via `check`.
fleet-bench:
	JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_bench.py

fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_bench.py --smoke

# Multihost smoke (benchmarks/multihost_bench.py): TWO real processes
# join a localhost coordinator (4 virtual CPU devices each, gloo
# collectives) and run the sharded lean profile — a measured rounds/s
# figure with bit-parity against the single-process 8-device run
# asserted in-band. ~1 min on a 1-core host.
multihost-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/multihost_bench.py --smoke

# What CI runs; a red suite, dirty lint, new analysis finding, a failed
# chaos soak, a sweep-amortization regression, a kernel-parity break,
# a multihost parity/measurement failure, a red byzantine-atlas
# baseline, a serve-tier encode-once/ratio regression, an
# overload-degradation regression (availability ratio, breaker
# opening, epoch monotonicity), a durability regression (warm rejoin
# ratio/speed, leave-vs-phi detection), a twin regression (held-out
# calibration error, one-compile autotune, recommendation-beats-
# default), a propagation-provenance regression (join coverage,
# measured-spread keys, staleness-oracle bit parity), a wire
# data-plane regression (fast-vs-control ratio, encode-call collapse,
# cache engagement), a fleet-telemetry regression (view coverage,
# staleness bound, watermark monotonicity, exact provenance joins),
# or a virtual-time regression (compression ratio, bit-identical
# seeded replay, long-horizon scenario verdicts) cannot land through
# this gate. (kernel-parity re-runs one test file that
# test-all also covers — the explicit target keeps the merge gate for
# kernel work nameable and runnable alone.)
check: lint analyze kernel-parity sweep-bench multihost-smoke atlas-smoke serve-smoke overload-smoke restart-smoke vtime-smoke twin-smoke prov-smoke wire-smoke fleet-smoke test-all

cov:
	@$(PY) -c "import pytest_cov" 2>/dev/null \
		|| (echo "pytest-cov not installed in this image; run 'make test'" && exit 1)
	$(PY) -m pytest tests/ -q --cov=aiocluster_tpu --cov-report=term-missing

# Regenerate protobuf stubs for third-party interop from the shipped
# schema (the framework's own codec is hand-rolled and needs no codegen;
# tests/test_wire_proto_file.py keeps schema and codec in sync).
protos:
	mkdir -p build/protogen
	protoc --proto_path=aiocluster_tpu/wire --python_out=build/protogen messages.proto
	@echo "generated build/protogen/messages_pb2.py"

smoke:
	$(PY) bench.py --smoke
	$(PY) __graft_entry__.py dryrun 8

# Zero-to-telemetry check (docs/observability.md): run a short traced sim
# with the metrics sampler on, then validate the trace is well-formed
# JSONL carrying the per-round convergence series.
obs-demo:
	rm -f build/obs_demo_trace.jsonl && mkdir -p build
	JAX_PLATFORMS=cpu $(PY) -m aiocluster_tpu sim --nodes 512 --keys 64 \
		--mtu 5000 --lean --cpu --max-rounds 256 --metrics-stride 2 \
		--trace-file build/obs_demo_trace.jsonl
	$(PY) -c "from aiocluster_tpu.obs import read_trace; \
		t = read_trace('build/obs_demo_trace.jsonl'); \
		assert t and t[0]['event'] == 'trace_header', t[:1]; \
		rounds = [e for e in t[1:] if e['event'] == 'sim_round']; \
		assert rounds and len(rounds) == len(t) - 1, t; \
		assert rounds[-1]['mean_fraction'] == 1.0, rounds[-1]; \
		print(f'obs-demo OK: {len(rounds)} sampled rounds, converged')"

clean:
	rm -rf build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
