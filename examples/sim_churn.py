"""Simulate a 1,024-node cluster under churn with full FD fidelity.

The sim-backend counterpart of examples/simple.py (reference
examples/simple.py:14-48 runs 3 real nodes; one jit'd tensor step here
advances 1,024): continuous 2% churn, FD-faithful peer selection, the
two-stage dead-node lifecycle, a mid-run checkpoint, and a resume that
continues the exact trajectory.

Run from a checkout:  python examples/sim_churn.py [--cpu]
(CPU-friendly: ~10 s. On a TPU the same script is just faster. ``--cpu``
pins the CPU backend — useful when an accelerator plugin is installed
but its device is unreachable.)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from aiocluster_tpu.core import DEFAULT_MAX_PAYLOAD_SIZE
from aiocluster_tpu.sim import SimConfig, Simulator, budget_from_mtu


def main() -> None:
    cfg = SimConfig(
        n_nodes=1024,
        keys_per_node=16,
        fanout=3,
        # The per-exchange bound IS the reference's default MTU,
        # converted by the exact wire-size accounting.
        budget=budget_from_mtu(DEFAULT_MAX_PAYLOAD_SIZE),
        writes_per_round=1,
        death_rate=0.02,
        revival_rate=0.1,
        peer_mode="view",  # peers drawn from each node's own live view
        pairing="choice",
        dead_grace_ticks=60,  # schedule at 30 dead rounds, forget at 60
    )
    sim = Simulator(cfg, seed=7, chunk=16, trace=True)

    sim.run(64)
    m = sim.metrics()
    alive = int(np.asarray(sim.state.alive).sum())
    print(f"tick {sim.tick}: {alive}/{cfg.n_nodes} alive, "
          f"mean replication {float(m['mean_fraction']):.3f}")

    # Checkpoint, keep running, then resume the checkpoint and verify the
    # resumed run reproduces the same trajectory (same seed, same ticks).
    with tempfile.TemporaryDirectory() as d:
        ckpt = os.path.join(d, "cluster.npz")
        sim.save(ckpt)
        sim.run(32)
        twin = Simulator.resume(ckpt)
        twin.run(32)
        same = np.array_equal(np.asarray(sim.state.w), np.asarray(twin.state.w))
        print(f"resume reproduces trajectory: {same}")
        assert same

    dead_stamps = int((np.asarray(sim.state.dead_since) > 0).sum())
    print(f"dead-stamped observer/owner pairs right now: {dead_stamps}")
    print("ok")


if __name__ == "__main__":
    main()
