"""Embed a cluster node in an HTTP service (parity with the reference's
FastAPI example examples/api/app.py, built on stdlib asyncio only since this
image ships no fastapi).

The handlers are the production serve tier (``aiocluster_tpu.serve``,
docs/serving.md) rather than hand-rolled HTTP parsing — which also
means the example gains the epoch-cached read path for free:

  GET  /state            -> cluster snapshot as JSON (ETag = state epoch,
                            If-None-Match -> 304, ?since=E -> delta)
  GET  /watch            -> long-poll for the next state change
  GET  /kv/<key>         -> this node's value for <key>
  PUT  /kv/<key>?v=...   -> set <key> on this node (replicates via gossip)
  PUT  /kv/<key>?v=...&ttl=1 -> set <key> with the TTL mark already applied
  DELETE /kv/<key>       -> tombstone <key>
  POST /kv_mark/<key>    -> mark <key> delete-after-TTL (reference
                            examples/api/app.py:100-113 /kv_mark parity)
  GET  /metrics          -> Prometheus text for this node's registry

Run two nodes and watch state replicate:
  python examples/http_api.py --port 8001 --gossip 7001 --seed 7002
  python examples/http_api.py --port 8002 --gossip 7002 --seed 7001
  curl -X PUT 'localhost:8001/kv/color?v=red'; sleep 2
  curl localhost:8002/state
  curl 'localhost:8002/watch?since=0'   # parks until the next change
"""

import argparse
import asyncio

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root run

from aiocluster_tpu import Cluster, Config, NodeId
from aiocluster_tpu.serve import ServeApp, encode_snapshot


def snapshot_json(cluster: Cluster) -> str:
    """The /state payload for a cluster (kept for importers of this
    example; the server below serves the identical bytes from the
    per-epoch cache instead of re-encoding per request)."""
    return encode_snapshot(cluster.snapshot()).decode()


async def serve_http(
    cluster: Cluster, port: int, started: asyncio.Event | None = None
) -> None:
    """Serve the HTTP API until cancelled. ``started`` (when given) is
    set once the listening socket is bound — callers that fire requests
    immediately (tests) wait on it instead of sleeping."""
    app = ServeApp(cluster)
    await app.start("127.0.0.1", port)
    if started is not None:
        started.set()
    try:
        await asyncio.Event().wait()  # serve until cancelled
    finally:
        await app.stop()


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8001, help="HTTP port")
    ap.add_argument("--gossip", type=int, default=7001, help="gossip port")
    ap.add_argument("--seed", type=int, action="append", default=[])
    args = ap.parse_args()

    config = Config(
        node_id=NodeId(
            name=f"api-{args.gossip}",
            gossip_advertise_addr=("127.0.0.1", args.gossip),
        ),
        gossip_interval=1.0,
        seed_nodes=[("127.0.0.1", p) for p in args.seed],
        cluster_id="http-api-demo",
    )
    async with Cluster(config) as cluster:
        print(f"http://127.0.0.1:{args.port}/state  (gossip on :{args.gossip})")
        await serve_http(cluster, args.port)


if __name__ == "__main__":
    asyncio.run(main())
