"""Embed a cluster node in an HTTP service (parity with the reference's
FastAPI example examples/api/app.py, built on stdlib asyncio only since this
image ships no fastapi).

Endpoints:
  GET  /state            -> cluster snapshot as JSON
  GET  /kv/<key>         -> this node's value for <key>
  PUT  /kv/<key>?v=...   -> set <key> on this node (replicates via gossip)
  PUT  /kv/<key>?v=...&ttl=1 -> set <key> with the TTL mark already applied
  DELETE /kv/<key>       -> tombstone <key>
  POST /kv_mark/<key>    -> mark <key> delete-after-TTL (reference
                            examples/api/app.py:100-113 /kv_mark parity)

Run two nodes and watch state replicate:
  python examples/http_api.py --port 8001 --gossip 7001 --seed 7002
  python examples/http_api.py --port 8002 --gossip 7002 --seed 7001
  curl -X PUT 'localhost:8001/kv/color?v=red'; sleep 2
  curl localhost:8002/state
"""

import argparse
import asyncio
import dataclasses
import json
from urllib.parse import parse_qs, urlparse

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root run

from aiocluster_tpu import Cluster, Config, NodeId


def snapshot_json(cluster: Cluster) -> str:
    snap = cluster.snapshot()
    return json.dumps(
        {
            "cluster_id": snap.cluster_id,
            "self": snap.self_node_id.name,
            "live": [n.name for n in snap.live_nodes],
            "dead": [n.name for n in snap.dead_nodes],
            "nodes": {
                n.name: {
                    k: s.get(k).value for k in list(s.key_values) if s.get(k)
                }
                for n, s in snap.node_states.items()
            },
            "hook_stats": dataclasses.asdict(cluster.hook_stats()),
        },
        indent=2,
    )


async def serve_http(
    cluster: Cluster, port: int, started: asyncio.Event | None = None
) -> None:
    """Serve the HTTP API until cancelled. ``started`` (when given) is
    set once the listening socket is bound — callers that fire requests
    immediately (tests) wait on it instead of sleeping."""
    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request = await reader.readline()
            while (await reader.readline()).strip():
                pass  # drain headers
            try:
                method, target, _ = request.decode().split()
            except ValueError:
                return
            url = urlparse(target)
            parts = url.path.strip("/").split("/")
            status, body = "404 Not Found", "not found"
            if url.path == "/state" and method == "GET":
                status, body = "200 OK", snapshot_json(cluster)
            elif len(parts) == 2 and parts[0] == "kv":
                key = parts[1]
                if method == "GET":
                    value = cluster.get(key)
                    if value is not None:
                        status, body = "200 OK", value
                elif method == "PUT":
                    query = parse_qs(url.query)
                    value = query.get("v", [""])[0]
                    if query.get("ttl", ["0"])[0] in ("1", "true"):
                        cluster.set_with_ttl(key, value)
                    else:
                        cluster.set(key, value)
                    status, body = "200 OK", "ok"
                elif method == "DELETE":
                    cluster.delete(key)
                    status, body = "200 OK", "ok"
            elif (
                len(parts) == 2 and parts[0] == "kv_mark" and method == "POST"
            ):
                # Grace-period delete: replicas keep serving the key until
                # its TTL elapses, then it tombstones cluster-wide.
                if cluster.get(parts[1]) is not None:
                    cluster.delete_after_ttl(parts[1])
                    status, body = "200 OK", "ok"
            payload = body.encode()
            writer.write(
                f"HTTP/1.1 {status}\r\nContent-Length: {len(payload)}\r\n"
                f"Content-Type: text/plain\r\n\r\n".encode() + payload
            )
            await writer.drain()
        finally:
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", port)
    if started is not None:
        started.set()
    async with server:
        await server.serve_forever()


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8001, help="HTTP port")
    ap.add_argument("--gossip", type=int, default=7001, help="gossip port")
    ap.add_argument("--seed", type=int, action="append", default=[])
    args = ap.parse_args()

    config = Config(
        node_id=NodeId(
            name=f"api-{args.gossip}",
            gossip_advertise_addr=("127.0.0.1", args.gossip),
        ),
        gossip_interval=1.0,
        seed_nodes=[("127.0.0.1", p) for p in args.seed],
        cluster_id="http-api-demo",
    )
    async with Cluster(config) as cluster:
        print(f"http://127.0.0.1:{args.port}/state  (gossip on :{args.gossip})")
        await serve_http(cluster, args.port)


if __name__ == "__main__":
    asyncio.run(main())
