"""Three-node in-process cluster demo (parity: reference examples/simple.py).

Each node seeds off the next in a ring, publishes one key, and after a few
gossip rounds every node's snapshot contains all three keyspaces.

Run: python examples/simple.py
"""

import asyncio
import logging

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root run

from aiocluster_tpu import Cluster, Config, NodeId


async def main() -> None:
    ports = [7000, 7001, 7002]
    configs = [
        Config(
            node_id=NodeId(
                name=f"simple{i + 1}",
                gossip_advertise_addr=("127.0.0.1", ports[i]),
            ),
            gossip_interval=1.0,
            seed_nodes=[("127.0.0.1", ports[(i + 1) % 3])],
            cluster_id="simple-aiocluster-tpu",
        )
        for i in range(3)
    ]
    clusters = [
        Cluster(cfg, initial_key_values={"cluster": str(i + 1)})
        for i, cfg in enumerate(configs)
    ]

    async with clusters[0], clusters[1], clusters[2]:
        await asyncio.sleep(5)
        for c in clusters:
            snap = c.snapshot()
            known = {
                n.name: {k: s.get(k).value for k in s.key_values if s.get(k)}
                for n, s in snap.node_states.items()
            }
            print(f"{snap.self_node_id.name}: sees {known}, "
                  f"live={[n.name for n in snap.live_nodes]}")


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    asyncio.run(main())
