"""Benchmark: batched TPU gossip simulation vs the pure-Python object model.

Headline metric (BASELINE.md): simulated gossip rounds/second at 10k nodes
(BASELINE config 4 scale) on one chip, full failure-detector fidelity.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is the measured speed of the equivalent pure-Python gossip round —
the reference's own execution model — extrapolated to the same cluster
size (an ESTIMATE, labelled as such in ``extra.baseline_kind``):
per-handshake cost is fit as t(N) = a + b*N over in-memory engine
handshakes (digest size grows with N), and a full round costs
N * fanout * t(N). The ratio is therefore "how many times faster one
process simulates the cluster than the asyncio object model could".
``extra.anchored_asyncio_3node_convergence_s`` is a real (measured, not
extrapolated) socket-backend datum: wall-clock for a 3-node loopback
cluster to full replication (BASELINE.md config 1).

Robustness (round-1 lesson): the accelerator platform is probed in a
SUBPROCESS with a timeout before this process commits to it — the TPU
plugin retries forever in-process when its tunnel is down, which turned
round 1's bench into rc=1/rc=124 artifacts. Bounded retries with backoff,
then (``--platform auto``) an explicit CPU fallback. Exactly ONE JSON
line is printed on stdout even on failure (with an ``error`` field);
diagnostics go to stderr.

Artifact shape (round-3 lesson): the stdout line is COMPACT — headline
scalars only, hard-capped at ``STDOUT_LINE_CAP`` bytes — because the
driver's capture truncated round 3's grown record into an unparseable
tail (BENCH_r03.json ``"parsed": null``). The full record, including
the embedded on-chip provenance chain and the measured reference
baseline, goes to ``benchmarks/records/bench_last_run.json``; the
stdout line carries a pointer to it.

Usage: python bench.py [--smoke] [--nodes N] [--rounds R]
                       [--platform {auto,tpu,cpu}]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time


def measure_python_handshake_seconds(n_nodes: int) -> float:
    """Mean wall-clock of one full in-memory 3-way handshake between two
    nodes of an ``n_nodes``-sized cluster view (object model, no sockets)."""
    from datetime import datetime

    from aiocluster_tpu.utils.clock import UTC

    from aiocluster_tpu.core import (
        ClusterState,
        Config,
        FailureDetector,
        FailureDetectorConfig,
        NodeId,
    )
    from aiocluster_tpu.runtime.engine import GossipEngine
    from aiocluster_tpu.wire import decode_packet, encode_packet

    ts = datetime(2026, 1, 1, tzinfo=UTC)
    nodes = [NodeId(f"n{i}", i + 1, ("h", i + 1)) for i in range(n_nodes)]

    def build_engine(self_idx: int, know_all: bool) -> GossipEngine:
        cfg = Config(node_id=nodes[self_idx], cluster_id="bench")
        cs = ClusterState()
        fd = FailureDetector(FailureDetectorConfig())
        population = nodes if know_all else [nodes[self_idx]]
        for k, node in enumerate(population):
            ns = cs.node_state_or_default(node)
            ns.heartbeat = 5  # noqa: ACT030 -- white-box: fabricating bench payload state, never gossiped
            for j in range(16):
                ns.set_with_version(f"key-{j:04d}", f"v{k}:{j}", j + 1, ts=ts)
        return GossipEngine(cfg, cs, fd)

    # One side knows the cluster, the other is missing a couple of nodes'
    # latest keys — the steady-state shape of a real round.
    a = build_engine(0, know_all=True)
    b = build_engine(1, know_all=True)
    for i in range(2, 5):
        ns = b._state.node_state_or_default(nodes[i])
        ns.set_with_version("fresh", "x", 17, ts=ts)

    reps = 5
    start = time.perf_counter()
    for _ in range(reps):
        syn = decode_packet(encode_packet(a.make_syn()))
        synack = decode_packet(encode_packet(b.handle_syn(syn)))
        ack = decode_packet(encode_packet(a.handle_synack(synack)))
        b.handle_ack(ack)
    return (time.perf_counter() - start) / reps


def python_rounds_per_sec(n_target: int) -> float:
    """Extrapolated whole-cluster rounds/sec for the object model."""
    n1, n2 = 128, 512
    t1 = measure_python_handshake_seconds(n1)
    t2 = measure_python_handshake_seconds(n2)
    b = max((t2 - t1) / (n2 - n1), 0.0)
    a = max(t1 - b * n1, 1e-9)
    t_target = a + b * n_target
    fanout = 3
    round_time = n_target * fanout * t_target
    return 1.0 / round_time


# Key-versions per exchange, derived from the reference's default
# max_payload_size (entities.py:105, core.DEFAULT_MAX_PAYLOAD_SIZE) by
# the exact wire-size accounting (sim.bytes.budget_from_mtu — 2,618 for
# the bench's 8-byte keys/values), so the sim's per-exchange bound IS the
# reference MTU, not an estimate.


def _mtu_bytes() -> int:
    from aiocluster_tpu.core import DEFAULT_MAX_PAYLOAD_SIZE

    return DEFAULT_MAX_PAYLOAD_SIZE


def _budget() -> int:
    from aiocluster_tpu.sim import budget_from_mtu

    return budget_from_mtu(_mtu_bytes())

PROBE_TIMEOUT_S = 120.0  # first TPU init+compile can take 20-40s; be generous
# Tunnel outages last hours; the default probe window stays short so an
# unattended bench still produces a (fallback-embedding) record quickly,
# but a caller who can afford to wait for the chip raises it from the
# environment (e.g. BENCH_PROBE_ATTEMPTS=40 ~= a 1.5 h window).
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
PROBE_BACKOFF_S = (15.0, 45.0)  # waits between attempts

# The tunnel watcher (benchmarks/records/_r3_tunnel_watch.py) appends one
# JSON line per tunnel state TRANSITION plus a 30-min heartbeat, so a
# recent last line is authoritative: if it says "down", the full
# 3x120s-probe ladder would spend ~7 min of the watchdog budget
# re-discovering a fact already on disk (BENCH_r04 did exactly that).
# In that case the bench does ONE short probe (the window may have just
# opened between watcher polls) and otherwise falls back immediately.
TUNNEL_LOG = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "benchmarks", "records", "r3_tunnel_log.jsonl",
)
TUNNEL_LOG_FRESH_S = 40 * 60.0  # heartbeat period + slack
# The recovery probe must outlast a cold backend init on a just-opened
# window (20-40s observed) while keeping a truly-down bench under the
# <60s-to-first-trial bar.
PROBE_TIMEOUT_KNOWN_DOWN_S = 45.0


def _tunnel_watcher_verdict(log, path: str = TUNNEL_LOG) -> str | None:
    """Last tunnel state the watcher recorded, if fresh: "up", "down",
    or None (no watcher, stale log, or unparseable — logged, so a bench
    that runs the full ladder says why the fast path was skipped)."""
    import calendar

    try:
        with open(path, "rb") as f:
            tail = f.read()[-4096:].decode("utf-8", "replace")
        line = [ln for ln in tail.strip().splitlines() if ln.strip()][-1]
        rec = json.loads(line)
        ts = calendar.timegm(time.strptime(rec["ts"], "%Y-%m-%dT%H:%M:%SZ"))
        age = time.time() - ts
        if not 0 <= age <= TUNNEL_LOG_FRESH_S:
            log(f"tunnel watcher log is stale (age {age:.0f}s); full probe ladder")
            return None
        state = rec.get("tunnel")
        if state not in ("up", "down"):
            log(f"tunnel watcher log has unknown state {state!r}; full probe ladder")
            return None
        return state
    except Exception as exc:
        log(f"no usable tunnel watcher log ({exc!r:.80}); full probe ladder")
        return None


def _probe_accelerator(log, timeout_s: float = PROBE_TIMEOUT_S) -> str:
    """Classify the default backend in a bounded time: ``"ok"`` (a real
    accelerator initialized), ``"cpu"`` (deterministically resolved to
    CPU — retrying is pointless), or ``"down"`` (timeout/crash — a flaky
    tunnel, worth retrying). Runs in a subprocess because a down TPU
    tunnel makes in-process backend init retry forever (uninterruptibly).
    """
    code = (
        "import jax, jax.numpy as jnp; "
        "x = jnp.zeros((8, 8)); "
        "print(jax.default_backend(), len(jax.devices()), float(x.sum()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        log(f"backend probe timed out after {timeout_s:.0f}s")
        return "down"
    if proc.returncode != 0:
        log(f"backend probe failed rc={proc.returncode}: "
            f"{proc.stderr.strip().splitlines()[-1] if proc.stderr.strip() else '?'}")
        return "down"
    # The probe's own print() is the LAST stdout line; site hooks may
    # emit noise before it.
    out = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    backend = out.split()[0] if out else ""
    if backend in ("", "cpu"):
        log(f"backend probe resolved to CPU, not an accelerator: {out!r}")
        return "cpu"
    log(f"backend probe ok: {out}")
    return "ok"


def resolve_platform(requested: str, log) -> None:
    """Pin this process's JAX platform BEFORE first device use (the
    caller reads the result off ``jax.default_backend()``). The explicit
    ``jax.config.update`` is required: the image's site hooks merge the
    accelerator back into ``jax_platforms`` even when the env says cpu
    (see tests/conftest.py)."""
    import jax

    if requested == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return
    watcher = _tunnel_watcher_verdict(log)
    if watcher == "down":
        log("tunnel watcher says down (fresh); single short probe only")
        verdict = _probe_accelerator(log, timeout_s=PROBE_TIMEOUT_KNOWN_DOWN_S)
        if verdict == "ok":
            return
        if requested == "tpu":
            # A 'cpu' verdict is a deterministic resolution (plugin
            # absent), not a tunnel outage — report it as the full
            # ladder would, so the diagnostic says what actually
            # happened instead of implying a flaky tunnel.
            if verdict == "cpu":
                raise RuntimeError(
                    "accelerator backend unavailable: probe resolved to "
                    "CPU, not an accelerator (watcher: down; 1 probe)"
                )
            raise RuntimeError(
                "accelerator backend unavailable (watcher: down; 1 probe)"
            )
        log("accelerator unavailable; falling back to CPU (--platform auto)")
        jax.config.update("jax_platforms", "cpu")
        return
    for attempt in range(PROBE_ATTEMPTS):
        verdict = _probe_accelerator(log)
        if verdict == "ok":
            return  # leave default platform selection alone
        if verdict == "cpu":
            break  # deterministic answer; backoff would be pointless
        if attempt < PROBE_ATTEMPTS - 1:
            wait = PROBE_BACKOFF_S[min(attempt, len(PROBE_BACKOFF_S) - 1)]
            log(f"retrying backend probe in {wait:.0f}s "
                f"({attempt + 1}/{PROBE_ATTEMPTS} failed)")
            time.sleep(wait)
    if requested == "tpu":
        raise RuntimeError(
            f"accelerator backend unavailable after {PROBE_ATTEMPTS} probes"
        )
    log("accelerator unavailable; falling back to CPU (--platform auto)")
    jax.config.update("jax_platforms", "cpu")


WATCHDOG_S = float(os.environ.get("BENCH_WATCHDOG_S", "2400"))


def _is_oom(exc: BaseException) -> bool:
    """XLA spells device OOM several ways ('RESOURCE_EXHAUSTED',
    'Resource exhausted: Out of memory while trying to allocate ...')."""
    msg = repr(exc).lower()
    return "resource_exhausted" in msg or "resource exhausted" in msg or (
        "out of memory" in msg
    )


def _start_watchdog(metric: str) -> None:
    """Guarantee the one-JSON-line contract even if the backend wedges
    mid-run (e.g. the tunnel drops AFTER a successful probe and the
    in-process plugin then retries forever): a daemon timer prints a
    diagnosable error line and hard-exits."""
    import threading

    def fire() -> None:
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": None,
                    "unit": "rounds/s",
                    "vs_baseline": None,
                    "error": f"watchdog: bench exceeded {WATCHDOG_S:.0f}s "
                    "(backend wedged mid-run?)",
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(WATCHDOG_S, fire)
    t.daemon = True
    t.start()


def _run_benchmarks_helper(module: str, func: str, log, /, *args, **kwargs):
    """Import ``benchmarks/<module>.py`` under a temporary sys.path entry
    and call ``func`` — the one scaffold for every measured-anchor probe
    below; a failure logs and returns None (the bench record reports
    what it could measure, never dies on an anchor)."""
    bench_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "benchmarks")
    sys.path.insert(0, bench_dir)
    try:
        import importlib

        fn = getattr(importlib.import_module(module), func)
        return fn(*args, **kwargs)
    except Exception as exc:
        log(f"{module}.{func} measurement failed: {exc!r}")
        return None
    finally:
        sys.path.remove(bench_dir)


def anchored_asyncio_seconds(log) -> float | None:
    """Real measured socket-backend anchor: 3-node loopback convergence
    (BASELINE.md config 1, reference examples/simple.py shape)."""
    record = _run_benchmarks_helper("run_all", "config1", log, smoke=False)
    if record is None:
        return None
    log(f"anchored asyncio 3-node convergence: {record['value']}s")
    return float(record["value"])


RECORDS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "benchmarks", "records"
)

# Round-3 window-1's headline, preserved only as stderr provenance (the
# bench record was nulled by a since-fixed crash; the window closed
# before a rerun). Embedded in CPU-fallback artifacts NEXT TO the
# certified chain, never in its place; superseded automatically the
# moment a battery run lands a certified record >= this.
UNCERTIFIED_BEST_ONCHIP = {
    "value": 67.5,
    "unit": "rounds/s",
    "n_nodes": 10_240,
    "source": "benchmarks/records/r3_window1_partial.json "
              "(stderr provenance; bench record nulled by a "
              "since-fixed crash)",
    # Machine-readable honesty flag (VERDICT item 8): this number's
    # anchor is NOT a certified bench record — consumers must not
    # promote it past the certified chain.
    "certified": False,
}


def analyzer_health(log) -> dict | None:
    """Run the repo's static analyzer in-process (tools/analyze: pure
    AST, ~1-2 s, no device) so every BENCH record carries
    correctness-tooling health next to the perf numbers — a perf
    trajectory over a dirty tree is not a trajectory worth chasing.
    ``analyze_clean`` is the `make check` gate verdict (no NEW findings
    under the committed baseline); ``analyze_findings`` counts new +
    grandfathered (suppressed judged-intentional sites excluded).
    ``analyze_duration_seconds`` keeps the gate honest about its own
    cost (budget: tests pin it under 10 s), and
    ``analyze_family_counts`` breaks actionable findings down per rule
    family (ACT00x..ACT05x) so a regression names its tier."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from tools.analyze import run_default

            t0 = time.perf_counter()
            report = run_default()
            duration = time.perf_counter() - t0
        finally:
            sys.path.pop(0)
        families: dict = {}
        for f in report.findings:
            if f.status in ("new", "baselined"):
                key = f.code[:5] + "x"
                families[key] = families.get(key, 0) + 1
        return {
            "analyze_clean": report.new == 0,
            "analyze_findings": report.new + report.count("baselined"),
            "analyze_duration_seconds": round(duration, 3),
            "analyze_family_counts": dict(sorted(families.items())),
        }
    except Exception as exc:
        log(f"analyzer health check failed: {exc!r}")
        return None


def load_last_onchip_record(log) -> dict | None:
    """The last committed on-chip bench record, embedded VERBATIM in
    CPU-fallback artifacts so a down tunnel can never reduce the
    certified evidence to a prose pointer (round-1/2 failure mode).
    latest_onchip.json is refreshed by every on-chip battery run
    (benchmarks/records/_r3_measure.py) and was seeded from the round-2
    certified record, so the chain never goes empty; the certified
    record itself is the fallback of the fallback."""
    for name in ("latest_onchip.json", "r02_builder_tpu_10240.json"):
        try:
            with open(os.path.join(RECORDS_DIR, name)) as f:
                rec = json.load(f)
        except Exception as exc:
            log(f"on-chip record {name} unavailable: {exc!r}")
            continue
        # One shape for every consumer: latest_onchip.json wraps the
        # bench record in {head, source, record}; the certified round-2
        # file IS the bare record — wrap it so downstream code (the
        # compact line, the uncertified-best comparison) reads only the
        # wrapped form.
        if "record" not in rec:
            rec = {"head": None, "source": name, "record": rec}
        return rec
    log("NO on-chip record embedded — fallback artifact is CPU-only "
        "(should not happen: records/ is committed)")
    return None


def fused_roofline_projection(last_onchip, log) -> dict | None:
    """PROJECTED fused-round roofline for a CPU-fallback record,
    anchored to the last certified on-chip measurement: at the chip's
    MEASURED sustained bandwidth, the fused kernel's minimal-traffic
    bytes/round (sim/bytes.py, variant="pairs" + fd_phase="fused")
    bounds the attainable round rate. Explicitly labelled a projection
    — the ≥0.6-of-peak claim is only ever made from an on-chip record
    with fd_kernel: true."""
    try:
        import re

        from aiocluster_tpu.sim import SimConfig
        from aiocluster_tpu.sim.bytes import per_round_bytes

        rec = (last_onchip or {}).get("record") or {}
        rps = rec.get("value")
        m = re.search(r"@(\d+)_nodes", str(rec.get("metric", "")))
        if not (rps and m):
            return None
        n = int(m.group(1))
        roof = (rec.get("extra") or {}).get("roofline") or {}
        kind = roof.get("device_kind") or "TPU v5 lite"
        peak = HBM_PEAK_GBPS.get(kind)
        cfg = SimConfig(
            n_nodes=n, keys_per_node=16, fanout=3, budget=2048,
            version_dtype="int16", heartbeat_dtype="int16",
            fd_dtype="bfloat16",
        )
        fused_bpr = per_round_bytes(cfg, variant="pairs", fd_phase="fused")
        # Sustained GB/s the chip actually demonstrated on this workload
        # (recorded, or reconstructed from the record's own path model).
        measured_gbps = roof.get("achieved_gb_per_sec")
        if measured_gbps is None:
            variant = (rec.get("extra") or {}).get(
                "pallas_variant_engaged", "m8"
            )
            fd_phase = (
                "kernel" if (rec.get("extra") or {}).get("fd_kernel")
                else "xla"
            )
            measured_gbps = (
                per_round_bytes(cfg, variant=variant, fd_phase=fd_phase)
                * rps / 1e9
            )
        return {
            "label": "PROJECTION — accelerator unreachable; anchored to "
                     "the last on-chip record, not a measured fused run",
            "certified": False,
            "anchor_rounds_per_sec": rps,
            "anchor_n_nodes": n,
            "measured_gb_per_sec": round(measured_gbps, 1),
            "fused_bytes_per_round": fused_bpr,
            "projected_rounds_per_sec_at_measured_gbps": round(
                measured_gbps * 1e9 / fused_bpr, 1
            ),
            "hbm_peak_gb_per_sec": peak,
            "target_fraction_of_peak": 0.6,
        }
    except Exception as exc:
        log(f"fused roofline projection unavailable: {exc!r}")
        return None


def load_northstar_record(log) -> dict | None:
    """The measured-and-certified 100k rounds-to-convergence (round 4):
    R and its v5e-8 projection ride every bench record so the flagship
    claim is machine-readable wherever the driver captures it."""
    try:
        with open(os.path.join(RECORDS_DIR,
                               "r4_northstar_100k_convergence.json")) as f:
            rec = json.load(f)
        out = {
            "rounds_to_convergence": rec["value"],
            "n_nodes": rec["n_nodes"],
            "certified": "DONE" in str(rec.get("certification", "")),
        }
        proj = rec.get("projection_v5e8") or {}
        if proj:
            out["projected_v5e8_seconds"] = proj.get(
                "projected_total_seconds"
            )
            out["meets_60s_target"] = proj.get("meets_target")
        return out
    except Exception as exc:
        log(f"northstar record unavailable: {exc!r}")
        return None


def load_full_profile_record(log) -> dict | None:
    """Round-5: the measured full-profile (heartbeats + FD) exact R at
    the largest N walked, with its mesh-certification status — the
    scale evidence for the profile the reference actually runs."""
    try:
        with open(os.path.join(RECORDS_DIR,
                               "r5_full_profile_convergence.json")) as f:
            rec = json.load(f)
        cert = {}
        try:
            with open(os.path.join(
                RECORDS_DIR, "r5_full_profile_certification.json"
            )) as f:
                cert = json.load(f)
        except Exception:
            pass
        # Numeric keys are the full-profile entries; "choice_<n>" keys
        # hold the choice-pairing data points.
        numeric = [int(k) for k in rec if k.isdigit()]
        out = {}
        if numeric:
            best_n = max(numeric)
            entry = rec[str(best_n)]
            c = cert.get(str(best_n), {})
            out = {
                "n_nodes": best_n,
                "rounds_to_convergence": entry["value"],
                "profile": entry.get("profile"),
                "mesh_certified": bool(
                    c.get("final", {}).get("ok")
                    and c.get("prefix", {}).get("ok")
                ),
            }
        # The reference-faithful independent-sampling datum rides along.
        choice_keys = [k for k in rec if k.startswith("choice_")]
        if choice_keys:
            ck = max(choice_keys, key=lambda k: int(k.split("_")[1]))
            cc = cert.get(ck, {})
            out["choice_pairing"] = {
                "n_nodes": rec[ck]["n_nodes"],
                "rounds_to_convergence": rec[ck]["value"],
                "mesh_certified": bool(
                    cc.get("final", {}).get("ok")
                    and cc.get("prefix", {}).get("ok")
                ),
            }
        return out or None
    except Exception as exc:
        log(f"full-profile record unavailable: {exc!r}")
        return None


def load_staleness_record(log) -> dict | None:
    """Round-5 dynamic-workload summary: prefer the battery's on-chip
    phase output; fall back to the CPU record (honestly labelled)."""
    try:
        # On-chip battery output first. Candidates order by the record's
        # own ISO-8601 ``ts`` — checkout/clone rewrites mtimes, so a
        # fresh clone would otherwise pick an arbitrary winner — exactly
        # as _pairs_proven_on_chip orders canary evidence; a ts-less
        # record competes via its mtime rendered on the same ISO scale,
        # with sub-second mtime breaking same-second ties.
        candidates = []
        for path in glob.glob(os.path.join(RECORDS_DIR, "*measurements*.json")):
            try:
                with open(path) as f:
                    rec = json.load(f)
            except Exception:
                continue
            phase = rec.get("staleness")
            if not (isinstance(phase, dict) and "error" not in phase):
                continue
            mtime = os.path.getmtime(path)
            iso = str(rec.get("ts") or "") or time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(mtime)
            )
            candidates.append(((iso, mtime), rec.get("head"), phase))
        if candidates:
            _, head, phase = max(candidates, key=lambda c: c[0])
            source = "battery (on-chip)" + (f" @ {head}" if head else "")
            return {"source": source, **phase}
        with open(os.path.join(RECORDS_DIR, "r5_staleness_cpu.json")) as f:
            rec = json.load(f)
        return {
            "source": "cpu (scaled-down; on-chip phase armed)",
            "n_nodes": rec["n_nodes"],
            "sustainable_writes_per_node_per_round": rec[
                "sustainable_writes_per_node_per_round"
            ],
            "burst_recovery": rec["burst_recovery"],
            "sustained": rec["sustained"],
        }
    except Exception as exc:
        log(f"dynamic-workload record unavailable: {exc!r}")
        return None


def measured_reference_baseline(log) -> dict | None:
    """The ACTUAL reference library (/root/reference), run live as a
    64-node loopback cluster, measured in sim-equivalent rounds/s and
    time-to-convergence (VERDICT r2 item 6: report a measured datum
    next to the extrapolation, using the same interop machinery that
    already gossips with the reference in tests)."""
    return _run_benchmarks_helper(
        "reference_baseline", "measure", log, 64, log=log
    )


def runtime_handshake_bench(log) -> dict | None:
    """The asyncio-backend fast-path trajectory datum: back-to-back
    Syn→SynAck→Ack handshakes/s at a 64-node view, no gossip-interval
    floor (benchmarks/handshake_bench.py) — pooled persistent channels
    vs the reference's connect-per-round lifecycle on the same code.
    Cheap (a few seconds, loopback only, no device), so it rides every
    record including smoke: the perf trajectory tracks the runtime
    backend, not only the sim."""
    return _run_benchmarks_helper("handshake_bench", "measure", log, log=log)


def multihost_bench(log, smoke: bool) -> dict | None:
    """The multihost trajectory datum (benchmarks/multihost_bench.py):
    a REAL 2-process localhost mesh (gloo CPU collectives) running the
    sharded lean profile — measured rounds/s with single-process
    bit-parity asserted in-band. Rides every record (smoke included):
    the multi-host path is first-class now, not a smoke line."""
    return _run_benchmarks_helper(
        "multihost_bench", "measure", log, smoke=smoke, log=log
    )


def packed_rung_engagement(log) -> dict | None:
    """Per-rung packed-kernel engagement (sim.memory.
    packed_kernel_engagement): does the u4r lean rung ride the pairs
    kernel's VMEM nibble codec, and do the shrunk/deep full-FD rungs
    fuse their packed bookkeeping — resolved through the same dispatch
    sim_step uses, as the chip would see it. Compacted into the stdout
    line as the comma-joined engaged-rung list."""
    try:
        from aiocluster_tpu.sim.memory import packed_kernel_engagement

        return packed_kernel_engagement()
    except Exception as exc:
        log(f"packed-rung engagement unavailable: {exc!r}")
        return None


def memory_ladder_models(log) -> dict | None:
    """The memory ladder's planning claims (sim.memory.ladder_models):
    deepest full-FD rung B/pair vs the 9.125 target + the modeled
    100k-on-8x16GB fit, and the lean ladder's largest modeled
    single-chip N per rung. Every entry carries ``certified: false`` —
    these are analytic projections until a tunnel window calibrates the
    measured-boundary table for the new execution paths."""
    try:
        from aiocluster_tpu.sim.memory import ladder_models

        return ladder_models()
    except Exception as exc:
        log(f"memory ladder models unavailable: {exc!r}")
        return None


def sweep_bench(log, smoke: bool) -> dict | None:
    """The multi-scenario throughput datum (benchmarks/sweep_bench.py):
    an S-lane vmapped sweep's wall time vs S sequential single-scenario
    runs of the same scenarios (each paying its own compile — distinct
    static configs), plus lane-rounds/s. Rides every record: the sweep
    engine is how scenario studies are meant to be run."""
    return _run_benchmarks_helper(
        "sweep_bench", "measure", log, smoke=smoke, log=log
    )


def convergence_under_fault_bench(log, smoke: bool) -> dict | None:
    """The robustness trajectory datum (benchmarks/fault_bench.py):
    time to re-converge after a 3-way partition heals — wall-clock
    seconds on a real 16-node loopback fleet AND gossip rounds in the
    batched sim (10k nodes full / 1,280 smoke), both driven by the same
    seeded split_brain FaultPlan (docs/faults.md). Rides every record:
    a perf gain that regressed reconvergence is not a gain."""
    return _run_benchmarks_helper(
        "fault_bench", "measure", log, smoke=smoke, log=log
    )


def byzantine_atlas_bench(log, smoke: bool) -> dict | None:
    """The wrong-data tolerance datum (benchmarks/byzantine_bench.py):
    the (byzantine fraction x phi_threshold x fanout) phase map, all
    cells as sweep lanes under one compile — headline
    ``byzantine_tolerated_frac`` is the largest attacker fraction the
    reference operating point (largest phi/fanout in the grid) rides
    out with honest convergence intact and honest-pair FD false
    positives under budget (docs/faults.md "byzantine")."""
    return _run_benchmarks_helper(
        "byzantine_bench", "measure", log, smoke=smoke, log=log
    )


def serve_tier_bench(log, smoke: bool) -> dict | None:
    """The serve-tier datum (benchmarks/serve_bench.py): epoch-cached
    snapshot fan-out against a real loopback fleet — 10k child-process
    long-poll watchers (64 in smoke) with measured encodes-per-epoch
    (must be ~1, not ~watchers) and wake p50/p99, plus the closed-loop
    cached vs walk-and-encode-per-request control reader ratio
    (docs/serving.md). The read path toward the millions-of-clients
    north star rides every record."""
    return _run_benchmarks_helper(
        "serve_bench", "measure", log, smoke=smoke, log=log
    )


def restart_durability_bench(log, smoke: bool) -> dict | None:
    """The durability datum (benchmarks/restart_bench.py,
    docs/robustness.md "Durability & lifecycle"): a rolling restart run
    warm (persistent store, graceful close, store-restored rejoin) vs
    cold (the reference's amnesiac reboot) on real loopback fleets —
    the warm/cold re-replication byte ratio and reconvergence, plus
    graceful-leave detection vs the measured phi window. Rides every
    record with its gate verdicts machine-readable."""
    return _run_benchmarks_helper(
        "restart_bench", "measure", log, smoke=smoke, log=log
    )


def vtime_runtime_bench(log, smoke: bool) -> dict | None:
    """The virtual-time datum (benchmarks/vtime_bench.py,
    docs/virtual-time.md): a 200-node loopback fleet driven through a
    full virtual hour of protocol time on the compressed clock (smoke:
    16 nodes, ten virtual minutes), the bit-identical seeded chaos
    replay measured rather than assumed, and the long-horizon scenario
    pack (dead-node GC lifecycle, week-long drift, slow-leak churn) —
    compression ratio and replay identity ride every record with the
    gate verdicts machine-readable."""
    return _run_benchmarks_helper(
        "vtime_bench", "measure", log, smoke=smoke, log=log
    )


def overload_degradation_bench(log, smoke: bool) -> dict | None:
    """The overload/degradation datum (benchmarks/overload_bench.py,
    docs/robustness.md): a slow-peer storm (adaptive timeouts + circuit
    breakers on a real loopback fleet) plus a reader surge against the
    serve tier's admission control, layer ON vs OFF at the same load —
    the graceful-degradation claim (availability ratio, breakers
    opened, adaptive p99) measured, not asserted."""
    return _run_benchmarks_helper(
        "overload_bench", "measure", log, smoke=smoke, log=log
    )


def propagation_provenance_bench(log, smoke: bool) -> dict | None:
    """The propagation-provenance datum (benchmarks/propagation_bench.py,
    docs/observability.md "Propagation & provenance"): one marked write
    on a real loopback fleet, its measured write→99%-visibility latency
    and hop-depth histogram joined from receiver-side provenance
    traces, next to the sim's wavefront prediction for the lifted
    config — plus the staleness-tensor oracle parity cells (int32 and
    u4r, unsharded and 2-shard where the device layout allows)."""
    return _run_benchmarks_helper(
        "propagation_bench", "measure", log, smoke=smoke, log=log
    )


def fleet_telemetry_bench(log, smoke: bool) -> dict | None:
    """The fleet-telemetry datum (benchmarks/fleet_bench.py,
    docs/observability.md "Fleet telemetry"): gossip-borne health
    digests + any-member fleet views measured through a split-brain
    heal on a real loopback fleet — view coverage, bounded per-entry
    staleness, monotone advertised watermarks — plus the exact
    provenance-join fraction with wire trace context on (100% direct
    joins, zero send-heuristic) and the sim's telemetry-wavefront
    prediction."""
    return _run_benchmarks_helper(
        "fleet_bench", "measure", log, smoke=smoke, log=log
    )


def twin_closed_loop_bench(log, smoke: bool) -> dict | None:
    """The digital-twin datum (benchmarks/twin_bench.py, docs/twin.md):
    a real loopback fleet recorded with twin-grade round tracing,
    replayed through the deterministic sim, the transfer function
    fitted on the first half of the trace and validated against the
    held-out second half, then the SLO autotuner driven over a
    candidate grid under ONE SweepSimulator compile — the calibrated
    rounds/s prediction and the recommended fanout ride every record
    with the gate verdicts machine-readable."""
    return _run_benchmarks_helper(
        "twin_bench", "measure", log, smoke=smoke, log=log
    )


# Hard cap on the stdout record line. Round 3's full record grew to
# ~4.5 KB and the driver's capture kept only an unparseable tail
# (BENCH_r03.json "parsed": null); the compact line stays ~an order of
# magnitude under this, and the cap is enforced (with a documented
# sacrifice order) so growth can never break the contract again.
STDOUT_LINE_CAP = 2000

# Keys dropped (in order) if the compact line somehow exceeds the cap —
# least-essential provenance first; the headline fields
# (metric/value/unit/vs_baseline) and platform are never dropped.
_SACRIFICE_ORDER = (
    "prov_exact_join_frac",
    "fleet_staleness_p99_s",
    "fleet_view_coverage_frac",
    "wire_bytes_copied_per_handshake",
    "wire_segment_hit_rate",
    "wire_fast_vs_control",
    "sim_wavefront_rounds",
    "propagation_hops_p99",
    "propagation_p99_s",
    "packed_kernel_engaged",
    "twin_recommended_fanout",
    "twin_predicted_rounds_per_sec",
    "leave_detect_seconds",
    "rejoin_warm_rounds",
    "rejoin_warm_vs_cold_bytes",
    "adaptive_timeout_p99_ms",
    "breaker_open_peers",
    "overload_availability_frac_control",
    "overload_availability_frac",
    "serve_encodes_per_epoch",
    "serve_cached_vs_control",
    "serve_watch_p99_ms",
    "serve_snapshots_per_sec",
    "atlas_cells",
    "byzantine_tolerated_frac",
    "budget",
    "full_fd_deepest_bytes_per_pair",
    "lean_max_scale_model_nodes",
    "multihost_rounds_per_sec",
    "sweep_amortization_ratio",
    "sim_sweep_lane_rounds_per_sec",
    "compile_cache_hit",
    "sim_fault_reconverge_rounds",
    "fault_reconverge_seconds",
    "runtime_handshakes_per_sec_per_round",
    "full_profile_n",
    "full_profile_r",
    "northstar_projected_v5e8_s",
    "northstar_rounds_100k",
    "reference_measured_rounds_per_sec",
    "xla_path_rounds_per_sec",
    "max_scale_rounds_per_sec",
    "roofline_gb_per_sec",
    "last_onchip_head",
    "max_scale_nodes",
    "last_onchip_value",
    "tpu_note",
    "full_record",
    "roofline_frac_fused_model",
    "pallas_variant",
    "fd_kernel",
    "pallas_speedup",
    "roofline_fraction_of_peak",
    "rounds_to_convergence",
)


def _compact_packed_engaged(eng) -> str | None:
    """The packed-rung engagement dict as one compact scalar: the
    comma-joined engaged rungs ("u4r,shrunk,deep"), "none" when the
    stamp exists but no packed rung rides a kernel (a loud value — the
    dispatch regressed), None when the stamp is absent."""
    if not isinstance(eng, dict):
        return None
    on = [rung for rung, engaged in eng.items() if engaged]
    return ",".join(on) if on else "none"


def compact_record(result: dict, record_path: str | None = None) -> dict:
    """The driver-facing stdout record: required headline fields plus a
    flat, scalar-only ``extra`` (no nested records — those live in the
    full-record file this points at)."""
    ex = result.get("extra", {})
    roof = ex.get("roofline") or {}
    ms = ex.get("max_scale_single_chip") or {}
    msb = ex.get("max_scale_single_chip_measured_boundary") or {}
    ref = (ex.get("measured_reference_library") or {}).get(
        "at_test_interval"
    ) or {}
    lo = ex.get("last_onchip") or {}
    lo_rec = lo.get("record") or {}
    hs = ex.get("runtime_handshake_bench") or {}
    fb = ex.get("fault_bench") or {}
    extra = {
        "platform": ex.get("platform"),
        "analyze_clean": ex.get("analyze_clean"),
        "analyze_findings": ex.get("analyze_findings"),
        "analyze_duration_seconds": ex.get("analyze_duration_seconds"),
        "runtime_handshakes_per_sec": (hs.get("pooled") or {}).get(
            "handshakes_per_sec"
        ),
        "runtime_handshakes_per_sec_per_round": (
            hs.get("per_round") or {}
        ).get("handshakes_per_sec"),
        # Zero-copy wire data plane (wire/segments.py): fast-vs-control
        # quiescent ratio, the write-arm segment hit rate, and write-
        # path bytes memcpy'd per handshake on the default config.
        "wire_fast_vs_control": hs.get("fast_vs_control"),
        "wire_segment_hit_rate": (
            ((hs.get("write_heavy") or {}).get("fast") or {}).get(
                "segment_hit_rate"
            )
        ),
        "wire_bytes_copied_per_handshake": (hs.get("pooled") or {}).get(
            "bytes_copied_per_handshake"
        ),
        # Reconvergence after a healed 3-way partition: wall-clock on
        # the 16-node runtime fleet, rounds in the sim arm.
        "fault_reconverge_seconds": (fb.get("runtime") or {}).get(
            "fault_reconverge_seconds"
        ),
        "sim_fault_reconverge_rounds": (fb.get("sim") or {}).get(
            "sim_fault_reconverge_rounds"
        ),
        # Wrong-data tolerance atlas headline: the largest byzantine
        # fraction the reference operating point rides out, + map size.
        "byzantine_tolerated_frac": (ex.get("byzantine_atlas") or {}).get(
            "byzantine_tolerated_frac"
        ),
        "atlas_cells": (ex.get("byzantine_atlas") or {}).get("atlas_cells"),
        # Serve tier: cached-read throughput, 10k-watcher wake p99, and
        # the measured encode-once + vs-control evidence (serve_bench).
        "serve_snapshots_per_sec": (ex.get("serve_bench") or {}).get(
            "serve_snapshots_per_sec"
        ),
        "serve_watch_p99_ms": (ex.get("serve_bench") or {}).get(
            "serve_watch_p99_ms"
        ),
        "serve_cached_vs_control": (ex.get("serve_bench") or {}).get(
            "cached_vs_control"
        ),
        "serve_encodes_per_epoch": (ex.get("serve_bench") or {}).get(
            "encodes_per_epoch"
        ),
        # Graceful degradation under overload (overload_bench.py):
        # shedding-arm availability vs the no-layer control at the same
        # load, breakers the slow-peer storm opened, and the p99
        # adaptive timeout in force on the fast subset.
        "overload_availability_frac": (ex.get("overload_bench") or {}).get(
            "overload_availability_frac"
        ),
        "overload_availability_frac_control": (
            ex.get("overload_bench") or {}
        ).get("overload_availability_frac_control"),
        "breaker_open_peers": (ex.get("overload_bench") or {}).get(
            "breaker_open_peers"
        ),
        "adaptive_timeout_p99_ms": (ex.get("overload_bench") or {}).get(
            "adaptive_timeout_p99_ms"
        ),
        # Durable node state (restart_bench.py): warm-vs-cold rolling
        # restart re-replication ratio, warm reconvergence, and the
        # graceful-leave detection time vs the phi window.
        "rejoin_warm_vs_cold_bytes": (ex.get("restart_bench") or {}).get(
            "rejoin_warm_vs_cold_bytes"
        ),
        "rejoin_warm_rounds": (ex.get("restart_bench") or {}).get(
            "rejoin_warm_rounds"
        ),
        "leave_detect_seconds": (ex.get("restart_bench") or {}).get(
            "leave_detect_seconds"
        ),
        # Virtual-time runtime (vtime_bench.py): how hard the
        # compressed clock compresses a real loopback hour, and whether
        # the seeded chaos replay stayed bit-identical this run.
        "vtime_compression_ratio": (ex.get("vtime_bench") or {}).get(
            "vtime_compression_ratio"
        ),
        "vtime_replay_identical": (ex.get("vtime_bench") or {}).get(
            "vtime_replay_identical"
        ),
        # Propagation provenance (propagation_bench.py): the marked
        # write's measured write→99%-visibility latency, its hop-depth
        # p99, and the sim's wavefront prediction for the lifted config.
        "propagation_p99_s": (ex.get("propagation_bench") or {}).get(
            "propagation_p99_s"
        ),
        "propagation_hops_p99": (ex.get("propagation_bench") or {}).get(
            "propagation_hops_p99"
        ),
        "sim_wavefront_rounds": (ex.get("propagation_bench") or {}).get(
            "sim_wavefront_rounds"
        ),
        # Fleet telemetry (fleet_bench.py): any-member view coverage,
        # bounded per-entry staleness, and the exact provenance-join
        # fraction with wire trace context on.
        "fleet_view_coverage_frac": (ex.get("fleet_bench") or {}).get(
            "fleet_view_coverage_frac"
        ),
        "fleet_staleness_p99_s": (ex.get("fleet_bench") or {}).get(
            "fleet_staleness_p99_s"
        ),
        "prov_exact_join_frac": (ex.get("fleet_bench") or {}).get(
            "prov_exact_join_frac"
        ),
        # Digital twin (twin_bench): the calibrated (held-out-validated)
        # wall-clock rate and the SLO autotuner's recommended fanout.
        "twin_predicted_rounds_per_sec": (ex.get("twin_bench") or {}).get(
            "twin_predicted_rounds_per_sec"
        ),
        "twin_recommended_fanout": (ex.get("twin_bench") or {}).get(
            "twin_recommended_fanout"
        ),
        # S-lane sweep throughput + compile amortization (sweep_bench).
        "sim_sweep_lane_rounds_per_sec": (ex.get("sweep_bench") or {}).get(
            "sim_sweep_lane_rounds_per_sec"
        ),
        "sweep_amortization_ratio": (ex.get("sweep_bench") or {}).get(
            "amortization_ratio"
        ),
        "compile_cache_hit": ex.get("compile_cache_hit"),
        # 2-process multihost measured figure (parity-gated) + the
        # ladder's headline planner claims (certified: false models).
        "multihost_rounds_per_sec": (ex.get("multihost_bench") or {}).get(
            "multihost_rounds_per_sec"
        ),
        "lean_max_scale_model_nodes": (
            (ex.get("memory_ladder") or {}).get("lean_max_scale_claim")
            or {}
        ).get("max_nodes_model"),
        # Which packed rungs ride the in-place Pallas path (comma-
        # joined; "none" = the dispatch regressed to the gather path).
        "packed_kernel_engaged": _compact_packed_engaged(
            ex.get("packed_kernel_engaged")
        ),
        "full_fd_deepest_bytes_per_pair": (
            (ex.get("memory_ladder") or {}).get("full_fd_deepest") or {}
        ).get("bytes_per_pair"),
        "rounds_to_convergence": ex.get("rounds_to_convergence"),
        "pallas_variant": ex.get("pallas_variant_engaged"),
        "pallas_speedup": ex.get("pallas_speedup"),
        "xla_path_rounds_per_sec": ex.get("xla_path_rounds_per_sec"),
        "fd_kernel": ex.get("fd_kernel"),
        "roofline_gb_per_sec": roof.get("achieved_gb_per_sec"),
        "roofline_fraction_of_peak": roof.get("fraction_of_peak"),
        # The fused minimal-traffic denominator's fraction rides the
        # compact line too: on-chip success for ROADMAP item 3 is
        # ">= 0.6 of HBM peak" measured against THIS model.
        "roofline_frac_fused_model": roof.get("roofline_frac_fused_model"),
        "max_scale_nodes": msb.get("nodes") or ms.get("nodes"),
        "max_scale_rounds_per_sec": (
            msb.get("rounds_per_sec") or ms.get("rounds_per_sec")
        ),
        "reference_measured_rounds_per_sec": ref.get(
            "sim_equivalent_rounds_per_sec"
        ),
        "northstar_rounds_100k": (ex.get("northstar_100k") or {}).get(
            "rounds_to_convergence"
        ),
        "northstar_projected_v5e8_s": (ex.get("northstar_100k") or {}).get(
            "projected_v5e8_seconds"
        ),
        "full_profile_r": (ex.get("full_profile_scale") or {}).get(
            "rounds_to_convergence"
        ),
        "full_profile_n": (ex.get("full_profile_scale") or {}).get("n_nodes"),
        "budget": ex.get("budget"),
        "tpu_note": ex.get("tpu_note"),
        # A CPU fallback still points at (and summarizes) the certified
        # on-chip evidence; the verbatim record is in the full file.
        "last_onchip_value": lo_rec.get("value"),
        "last_onchip_head": lo.get("head"),
        "full_record": record_path,
    }
    extra = {k: v for k, v in extra.items() if v is not None}
    line = {
        "metric": result["metric"],
        "value": result["value"],
        "unit": result["unit"],
        "vs_baseline": result["vs_baseline"],
        "extra": extra,
    }
    for key in _SACRIFICE_ORDER:
        if len(json.dumps(line)) <= STDOUT_LINE_CAP:
            break
        extra.pop(key, None)
    return line


def write_full_record(result: dict, log) -> str | None:
    """Persist the complete record (nested provenance and all) next to
    the other committed measurement records; returns the repo-relative
    path for the stdout pointer, or None if the write failed (the
    compact line must still be emitted)."""
    rel = os.path.join("benchmarks", "records", "bench_last_run.json")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), rel)
    payload = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "record": result,
    }
    try:
        with open(path + ".tmp", "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(path + ".tmp", path)
        return rel
    except Exception as exc:
        log(f"full-record write failed: {exc!r}")
        return None


# Published HBM bandwidth by PJRT device_kind (the axon tunnel reports
# "TPU v5 lite" for v5e).
HBM_PEAK_GBPS = {
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v4": 1228.0,
    "TPU v5": 2765.0,  # v5p
    "TPU v6 lite": 1640.0,  # v6e / Trillium
}


# The per-round HBM-traffic model lives with the sim
# (aiocluster_tpu.sim.bytes.per_round_bytes / roofline_models): one
# accounting shared by the bench roofline and any planner that wants a
# bandwidth estimate, keyed by the SAME variant/fd-phase resolutions
# sim_step dispatches on.


def sim_rounds_per_sec(
    n_nodes: int,
    rounds: int,
    log,
    max_converge_rounds: int | None = None,
) -> tuple[float, int | None, dict]:
    import jax
    import numpy as np

    from aiocluster_tpu.sim import SimConfig, Simulator

    # int16 knowledge matrices: exact for this workload (versions ≤ 16,
    # horizon ≪ 32768 ticks — see SimConfig.version_dtype) and half the
    # HBM traffic of int32, which is what the round time is made of.
    cfg = SimConfig(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=_budget(),
        version_dtype="int16",
        heartbeat_dtype="int16",
        fd_dtype="bfloat16",
    )
    sim = Simulator(cfg, seed=0, chunk=min(rounds, 16))
    # The Simulator folds the AIOCLUSTER_TPU_PALLAS_VARIANT override into
    # its config (jit-cache-key correctness, ADVICE r3); all provenance
    # below must describe THAT config, not the one we passed in.
    cfg = sim.cfg
    log(f"devices: {jax.devices()}")

    def sync() -> int:
        # block_until_ready does not reliably block through the axon
        # tunnel; a scalar device->host readback provably does.
        return int(np.asarray(sim.state.tick))

    # Warm-up: compile + first chunk. If the pair-fused kernel's first
    # real-Mosaic compile fails HERE (the driver runs bench.py outside
    # the battery's canary pin), fall back to the proven single-pass
    # kernel rather than losing the certification record — the variants
    # are bit-identical, only speed differs. One same-variant retry
    # first separates a transient tunnel blip from a deterministic
    # Mosaic rejection, and the guard requires the Pallas path to have
    # actually engaged (a CPU fallback's host-side error is not the
    # kernel's fault).
    t0 = time.perf_counter()
    try:
        sim.run(sim.chunk)
        sync()
    except Exception as first_exc:
        from aiocluster_tpu.ops.gossip import (
            pallas_path_engaged,
            pallas_variant_engaged,
        )

        if (
            _is_oom(first_exc)
            or not pallas_path_engaged(cfg)
            or pallas_variant_engaged(cfg) != "pairs"
        ):
            raise
        log(f"warm-up failed with the pairs kernel ({first_exc!r}); "
            "retrying same-variant once")
        try:
            sim = Simulator(cfg, seed=0, chunk=min(rounds, 16))
            sim.run(sim.chunk)
            sync()
        except Exception as second_exc:
            if _is_oom(second_exc):
                raise
            log(f"pairs kernel failed twice ({second_exc!r}); "
                "falling back to the single-pass kernel")
            import dataclasses

            # The explicit pin beats any AIOCLUSTER_TPU_PALLAS_VARIANT
            # override (resolve_variant_env precedence), so the rebuilt
            # Simulator really dispatches m8 even when the env exported
            # "pairs" (ADVICE r3).
            cfg = dataclasses.replace(cfg, pallas_variant="m8")
            sim = Simulator(cfg, seed=0, chunk=min(rounds, 16))
            cfg = sim.cfg
            sim.run(sim.chunk)
            sync()
    compile_first_chunk_s = time.perf_counter() - t0
    log(f"compile+first chunk: {compile_first_chunk_s:.1f}s")

    # The tunnel to the TPU is shared and noisy; take the best of three
    # trials as the device's attainable rate.
    rps = 0.0
    for trial in range(3):
        start = time.perf_counter()
        sim.run(rounds)
        end_tick = sync()
        elapsed = time.perf_counter() - start
        rps = max(rps, rounds / elapsed)
        log(
            f"trial {trial}: {rounds} rounds in {elapsed:.2f}s "
            f"-> {rounds / elapsed:.1f} rounds/s (tick={end_tick})"
        )

    # Telemetry-overhead arm (obs/): the same config with the stride-64
    # metrics sampler attached — the BENCH record carries the measured
    # cost of leaving metrics on, and the registry snapshot itself.
    extra: dict = {"compile_first_chunk_seconds": round(compile_first_chunk_s, 2)}
    try:
        from aiocluster_tpu.obs import MetricsRegistry

        obs_registry = MetricsRegistry()
        sim_m = Simulator(
            cfg, seed=0, chunk=sim.chunk,
            metrics=obs_registry, metrics_stride=64,
        )
        sim_m.run(sim_m.chunk)
        int(np.asarray(sim_m.state.tick))
        metrics_rps = 0.0
        for _ in range(2):
            start = time.perf_counter()
            sim_m.run(rounds)
            int(np.asarray(sim_m.state.tick))
            metrics_rps = max(
                metrics_rps, rounds / (time.perf_counter() - start)
            )
        sim_m.flush_metrics()
        extra["metrics_overhead"] = {
            "stride": 64,
            "rounds_per_sec_with_metrics": round(metrics_rps, 2),
            "fraction_of_metrics_off": (
                round(metrics_rps / rps, 4) if rps else None
            ),
        }
        extra["metrics_snapshot"] = obs_registry.snapshot()
        del sim_m
        log(f"metrics-on rate (stride 64): {metrics_rps:.1f} rounds/s "
            f"({metrics_rps / rps:.1%} of metrics-off)" if rps else
            "metrics-on rate measured")
    except Exception as exc:
        log(f"metrics overhead arm failed: {exc!r}")

    # The XLA-path rate for the same config: records the fused Pallas
    # kernel's measured speedup (VERDICT r1 item 3) without trusting the
    # default gate to have engaged.
    from aiocluster_tpu.ops.gossip import fd_phase_engaged, pallas_path_engaged

    # The exact gates sim_step used: only claim fused-path numbers when
    # the kernels actually engaged for this run. ``fd_kernel`` and the
    # FD phase come from THE resolution sim_step dispatches on
    # (fd_phase_engaged) — not a parallel probe — so the stamp can
    # never drift from what the compiled step did (the drift class
    # pallas_path_engaged's docstring warns about).
    fused = pallas_path_engaged(cfg)
    fd_phase = fd_phase_engaged(cfg)
    extra["fd_phase"] = fd_phase
    extra["fd_kernel"] = fd_phase in ("fused", "kernel")
    if fused:
        try:
            import dataclasses

            # The baseline arm must be the FULL XLA path: use_pallas_fd
            # pinned off too, or a forced FD kernel (use_pallas_fd=True)
            # would leak into the "XLA" rate and skew pallas_speedup.
            sim_x = Simulator(
                dataclasses.replace(
                    cfg, use_pallas=False, use_pallas_fd=False
                ),
                seed=0, chunk=sim.chunk,
            )
            sim_x.run(sim_x.chunk)
            int(np.asarray(sim_x.state.tick))
            xla_rps = 0.0
            # Same trial count as the fused measurement: best-of-N on the
            # noisy tunnel must be apples-to-apples or the ratio skews.
            for _ in range(3):
                start = time.perf_counter()
                sim_x.run(rounds)
                int(np.asarray(sim_x.state.tick))
                xla_rps = max(xla_rps, rounds / (time.perf_counter() - start))
            extra["xla_path_rounds_per_sec"] = round(xla_rps, 2)
            extra["pallas_speedup"] = (
                round(rps / xla_rps, 3) if xla_rps else None
            )
            log(f"XLA-path rate: {xla_rps:.1f} rounds/s "
                f"(pallas speedup {rps / xla_rps:.2f}x)")
        except Exception as exc:
            log(f"XLA-path comparison failed: {exc!r}")

        # Which pull-kernel implementation served the run — THE decision
        # function sim_step dispatches on, so the recorded variant and
        # the analytic bytes/round below (pairs: 2 passes per matrix per
        # sub-exchange; m8: 3) can never drift from what actually ran.
        from aiocluster_tpu.ops.gossip import pallas_variant_engaged
        from aiocluster_tpu.sim.bytes import roofline_models

        variant = pallas_variant_engaged(cfg)
        extra["pallas_variant_engaged"] = variant

        # Roofline: analytic bytes/round of the ENGAGED path vs the
        # chip's HBM peak (only meaningful when the fused path ran on
        # the real chip), plus the same achieved rate expressed against
        # the two reference denominators — the fully-fused
        # minimal-traffic model (one read+write of w/hb per
        # sub-exchange, FD riding the last one: the ROADMAP-item-3
        # target's denominator) and the plain-XLA model. The peak is
        # keyed by device kind; unknown chips get the numbers without
        # fractions rather than wrong ones.
        models = roofline_models(cfg, variant=variant, fd_phase=fd_phase)
        bpr = models["engaged"]
        achieved = bpr * rps / 1e9
        kind = jax.devices()[0].device_kind
        peak = HBM_PEAK_GBPS.get(kind)
        extra["roofline"] = {
            "bytes_per_round": bpr,
            "achieved_gb_per_sec": round(achieved, 1),
            "device_kind": kind,
            "hbm_peak_gb_per_sec": peak,
            "fraction_of_peak": (
                round(achieved / peak, 3) if peak else None
            ),
            "bytes_per_round_fused_model": models["fused"],
            "bytes_per_round_xla_model": models["xla"],
            "roofline_frac_fused_model": (
                round(models["fused"] * rps / 1e9 / peak, 3) if peak else None
            ),
            "roofline_frac_xla_model": (
                round(models["xla"] * rps / 1e9 / peak, 3) if peak else None
            ),
        }
        log(f"roofline: {bpr / 1e9:.2f} GB/round -> {achieved:.0f} GB/s"
            + (f" ({achieved / peak:.0%} of {kind} peak)" if peak else
               f" (unknown peak for {kind!r})"))

    # Convergence from a FRESH cluster (the timing runs above have long
    # converged this one) — with the obs sampler on, so the record also
    # carries the per-chunk convergence-fraction / delta-bytes series.
    t0 = time.perf_counter()
    try:
        from aiocluster_tpu.obs import MetricsRegistry

        conv_registry = MetricsRegistry()
        fresh = Simulator(
            cfg, seed=1, chunk=sim.chunk,
            metrics=conv_registry, metrics_stride=sim.chunk,
        )
    except Exception as exc:
        log(f"convergence probe metrics unavailable: {exc!r}")
        fresh = Simulator(cfg, seed=1, chunk=sim.chunk)
    # Cap the horizon inside the int16 heartbeat/tick contract (< 2^15);
    # the caller lowers the cap further on a CPU fallback, where this
    # probe is the dominant cost (watchdog budget).
    converged_at = fresh.run_until_converged(
        max_rounds=min(4 * n_nodes, 30_000, max_converge_rounds or 30_000)
    )
    try:
        series = fresh.flush_metrics()
        if series:
            # Bounded embed: the full record must stay a sane size.
            extra["convergence_series"] = [
                {
                    k: s.get(k)
                    for k in ("tick", "mean_fraction", "min_fraction",
                              "version_spread", "delta_key_versions",
                              "delta_bytes_est")
                    if k in s
                }
                for s in series[-64:]
            ]
    except Exception as exc:
        log(f"convergence series flush failed: {exc!r}")
    log(
        f"rounds to full convergence @ {n_nodes} nodes: {converged_at} "
        f"({time.perf_counter() - t0:.1f}s wall)"
    )
    return rps, converged_at, extra


# Largest 128-aligned lean population a single 16 GB chip should hold:
# the pair-fused kernel updates in place (one resident copy, 2 B/pair =
# 8.6 GB at this N) and its VMEM tile budget caps the width at 65,536.
# benchmarks/run_all.py::_fit_population arrives at the same number for
# n_devices=1 (pinned by tests/test_benchmarks.py). The old 52,096
# figure assumed the non-aliased two-copy path — which the chip refuted
# by OOM (round-3 window 1); the measured-boundary ladder walks down
# from this ceiling to whatever actually executes.
MAX_LEAN_SINGLE_CHIP = 65_536


def _planner_verdict_summary(log) -> dict | None:
    """fits_verdict for the single-chip lean ceiling, compacted for the
    record: carries the measured/model provenance split."""
    try:
        from aiocluster_tpu.sim.memory import fits_verdict, lean_config

        v = fits_verdict(lean_config(MAX_LEAN_SINGLE_CHIP))
        return {
            "nodes": MAX_LEAN_SINGLE_CHIP,
            "fits": v["fits"],
            "measured": v["measured"],
            # The machine-readable honesty flag: a verdict resting on
            # the analytic model alone is NOT certified (VERDICT item 8
            # — the flag rides the record, not just prose notes).
            "certified": bool(v["measured"]),
            "evidence_source": (v["evidence"] or {}).get("source"),
            "per_shard_bytes": v["per_shard_bytes"],
        }
    except Exception as exc:
        log(f"planner verdict unavailable: {exc!r}")
        return None


# One source of truth for the default scale-probe population: the
# boundary table records outcomes against this exact n (ADVICE r4, low).
SCALE_PROBE_N = 32_768


def scale_probe(log, n_nodes: int = SCALE_PROBE_N, rounds: int = 16) -> float:
    """Max single-chip scale: the lean convergence profile (int16
    watermarks, no FD matrices — sim/memory.py) at the largest N that fits
    one chip's HBM. The 100k-node north star runs this profile sharded
    over a v5e-8 (BASELINE.md config 5); this records the per-chip rate
    the projection is built on."""
    import numpy as np

    from aiocluster_tpu.sim import Simulator
    from aiocluster_tpu.sim.memory import fits_verdict, lean_config

    cfg = lean_config(n_nodes)
    # Advisory only: the chip is the authority on fit (the ladder exists
    # because the model has been wrong) — an AssertionError here would
    # kill the whole ladder instead of letting the rung OOM and walk on.
    v = fits_verdict(cfg)
    log(f"scale probe @ {n_nodes}: planner says fits={v['fits']} "
        f"(measured={v['measured']})")
    sim = Simulator(cfg, seed=0, chunk=8)
    t0 = time.perf_counter()
    sim.run(8)
    int(np.asarray(sim.state.tick))
    log(f"scale probe compile+first chunk: {time.perf_counter() - t0:.1f}s")
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        sim.run(rounds)
        int(np.asarray(sim.state.tick))
        best = max(best, rounds / (time.perf_counter() - t0))
    log(f"scale probe @ {n_nodes} nodes (lean): {best:.1f} rounds/s")
    return best


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="small CPU-friendly run")
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--rounds", type=int, default=None)
    parser.add_argument(
        "--platform",
        choices=("auto", "tpu", "cpu"),
        default=None,
        help="auto = probe the accelerator with retries, fall back to CPU; "
        "tpu = require it; cpu = pin CPU (default: auto, cpu when --smoke)",
    )
    args = parser.parse_args()

    # 10,240 = the 10k-class scale on aligned shapes: a multiple of 128
    # keeps every matrix tile-exact (no padded lanes), which the fused
    # Pallas kernel requires and which is measurably faster even on the
    # plain XLA path (36.8 vs 30.6 rounds/s at 10,000).
    n_nodes = args.nodes or (512 if args.smoke else 10_240)
    rounds = args.rounds or (32 if args.smoke else 64)

    def log(msg: str) -> None:
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    # int16 heartbeat contract: warmup + 3 timed trials must stay < 2^15
    # ticks (SimConfig.heartbeat_dtype).
    if rounds > 10_000:
        log(f"--rounds {rounds} capped to 10000 (int16 tick horizon)")
        rounds = 10_000

    metric = f"sim_gossip_rounds_per_sec@{n_nodes}_nodes"
    t_main = time.perf_counter()  # the watchdog's clock, for probe budgets
    _start_watchdog(metric)
    try:
        requested = args.platform or ("cpu" if args.smoke else "auto")
        resolve_platform(requested, log)
        import jax

        platform = jax.default_backend()
        log(f"platform: {platform}")

        # Persistent XLA compilation cache (utils/xla_cache.py): a warm
        # cache lets a second bench run skip the ~30 s sim compile. The
        # entry counts around the sim phase are the hit/miss probe.
        from aiocluster_tpu.utils.xla_cache import (
            enable_persistent_cache,
            entry_count,
        )

        xla_cache_dir = enable_persistent_cache(log=log)
        cache_entries_before = entry_count(xla_cache_dir)

        from aiocluster_tpu.ops.gossip import on_accelerator

        on_accel = on_accelerator()
        if not on_accel and not args.smoke and args.rounds is None:
            # CPU fallback of the full config: keep the record diagnosable
            # without racing the watchdog (a 10k-node CPU round is ~2-3
            # orders slower than the chip's).
            rounds = min(rounds, 16)
            log(f"CPU fallback: rounds capped to {rounds}")

        rps, converged_at, sim_extra = sim_rounds_per_sec(
            n_nodes, rounds, log,
            # The convergence probe dominates a CPU fallback; 64 rounds
            # is twice the chip-measured convergence point at 10k, so a
            # non-null answer is still possible without racing the
            # watchdog.
            max_converge_rounds=None if on_accel or args.smoke else 64,
        )
        # Cache verdict for the SIM phase specifically (snapshot before
        # later phases compile their own programs): a warm cache writes
        # no new entries, so before == after (> 0) means every compile
        # was served from disk.
        cache_entries_after = entry_count(xla_cache_dir)
        compile_cache_hit = bool(
            xla_cache_dir
            and cache_entries_before > 0
            and cache_entries_after == cache_entries_before
        )
        sim_extra["compile_cache"] = {
            "dir": xla_cache_dir,
            "entries_before": cache_entries_before,
            "entries_after": cache_entries_after,
        }
        sim_extra["compile_cache_hit"] = compile_cache_hit
        log(f"compile cache: {cache_entries_before} -> "
            f"{cache_entries_after} entries (hit={compile_cache_hit})")
        baseline_rps = python_rounds_per_sec(n_nodes)
        log(f"python object-model estimate: {baseline_rps:.4f} rounds/s")
        probe_rps = None
        probe_max_rps = None
        probe_max_n = None
        if not args.smoke and on_accel:
            from aiocluster_tpu.sim.memory import (
                fits_verdict,
                lean_config,
                record_boundary,
            )

            def note_boundary(n, fits, rps=None):
                # Every on-chip outcome calibrates the planner (round-3
                # lesson: the model's 52k claim OOM'd). CPU runs never
                # reach here — only chip outcomes enter the table.
                try:
                    record_boundary(
                        lean_config(n), 1, fits, rounds_per_sec=rps,
                        source="bench.py max-scale ladder (on-chip)",
                    )
                except Exception as exc:
                    log(f"boundary record failed: {exc!r}")

            try:
                probe_rps = round(scale_probe(log), 2)
                note_boundary(SCALE_PROBE_N, True, probe_rps)
            except Exception as exc:  # keep the headline even if the probe dies
                log(f"scale probe failed: {exc!r}")
                if _is_oom(exc):
                    note_boundary(SCALE_PROBE_N, False)
            # Walk the 128-aligned ladder down from the in-place pairs
            # ceiling (65,536 — one resident copy) to the largest N
            # that actually executes and record that boundary; 52,096
            # is the old two-copy claim the chip OOM'd on. Each rung
            # pays a full compile, so stop while the watchdog still
            # has room to emit the measurements already taken. Rungs
            # the measured table already rules out are skipped (the
            # planner consults hardware truth before the model).
            for probe_n in (MAX_LEAN_SINGLE_CHIP, 61_440, 57_344, 52_096,
                            45_056):
                if time.perf_counter() - t_main > WATCHDOG_S - 600:
                    log("max-scale ladder stopped: watchdog budget low")
                    break
                verdict = fits_verdict(lean_config(probe_n))
                if verdict["measured"] and not verdict["fits"]:
                    log(f"max-scale rung {probe_n} skipped: measured "
                        f"no-fit ({verdict['evidence']['source']})")
                    continue
                try:
                    probe_max_rps = round(scale_probe(log, n_nodes=probe_n), 2)
                    probe_max_n = probe_n
                    note_boundary(probe_n, True, probe_max_rps)
                    break
                except Exception as exc:
                    log(f"max-scale probe at {probe_n} failed: {exc!r}")
                    if not _is_oom(exc):
                        break  # not an OOM — don't hammer a sick tunnel
                    note_boundary(probe_n, False)
        anchored = None if args.smoke else anchored_asyncio_seconds(log)
        ref_measured = None if args.smoke else measured_reference_baseline(log)
        # Cheap and device-free: measured on every record, smoke included.
        hs_bench = runtime_handshake_bench(log)
        # Convergence-under-fault: the robustness companion to the
        # handshake datum, also on every record (sim arm at 10k nodes
        # in full runs, 1,280 in smoke).
        fault_rec = convergence_under_fault_bench(log, args.smoke)
        # Wrong-data tolerance atlas (byzantine_bench.py): always the
        # smoke grid inside bench.py — `make atlas` owns the full map.
        byz_rec = byzantine_atlas_bench(log, smoke=True)
        # Sweep engine: S-lane vmapped multi-scenario wall time vs S
        # sequential single-scenario runs (compile amortization is the
        # point — benchmarks/sweep_bench.py).
        sweep_rec = sweep_bench(log, args.smoke)
        # Multihost: measured 2-process rounds/s with single-process
        # bit-parity asserted (benchmarks/multihost_bench.py); on every
        # record — the MULTICHIP smoke line grew into a figure.
        mh_rec = multihost_bench(log, args.smoke)
        # Serve tier: snapshot fan-out + long-poll watchers against a
        # real loopback fleet (benchmarks/serve_bench.py) — 10k
        # watchers in full runs, 64 in smoke.
        serve_rec = serve_tier_bench(log, args.smoke)
        # Overload & degradation: slow-peer storm + reader surge with
        # the robustness layer on vs off (benchmarks/overload_bench.py).
        overload_rec = overload_degradation_bench(log, args.smoke)
        # Durable node state: warm-vs-cold rolling restart + leave
        # detection on real loopback fleets (restart_bench.py).
        restart_rec = restart_durability_bench(log, args.smoke)
        # Virtual-time runtime: the compressed-clock compression ratio,
        # bit-identical seeded replay, and the long-horizon scenario
        # pack (vtime_bench.py, docs/virtual-time.md).
        vtime_rec = vtime_runtime_bench(log, args.smoke)
        # Digital twin closed loop: recorded fleet trace -> replay ->
        # held-out-validated calibration -> one-compile SLO autotune
        # (twin_bench.py, docs/twin.md).
        twin_rec = twin_closed_loop_bench(log, args.smoke)
        # Propagation provenance: measured marked-write spread (latency
        # + hops) vs the sim's wavefront prediction, plus the staleness
        # oracle parity cells (propagation_bench.py).
        prov_rec = propagation_provenance_bench(log, args.smoke)
        # Fleet telemetry plane: any-member views + exact wire-level
        # provenance joins through a split-brain heal (fleet_bench.py,
        # docs/observability.md "Fleet telemetry").
        fleet_rec = fleet_telemetry_bench(log, args.smoke)
        # A CPU-fallback record is still a valid run, but its headline is
        # not the chip's — point the reader at the preserved on-chip
        # measurement so a down tunnel can't erase the evidence again
        # (round-1 failure mode).
        tpu_note = None
        last_onchip = None
        if not on_accel and not args.smoke and requested == "auto":
            tpu_note = (
                "accelerator unreachable at run time; last on-chip record: "
                "benchmarks/records/ (see its README for provenance)"
            )
            last_onchip = load_last_onchip_record(log)
            # The best on-chip measurement NOT yet in a certified bench
            # record (round-3 window 1 ended before the record landed;
            # the numbers survive as stderr provenance). Labelled
            # uncertified — never substituted for the certified chain.
            if last_onchip and (
                (last_onchip.get("record") or {}).get("value") or 0
            ) < UNCERTIFIED_BEST_ONCHIP["value"]:
                last_onchip = dict(last_onchip)
                last_onchip["uncertified_best"] = UNCERTIFIED_BEST_ONCHIP
        # The fused-round roofline stays a LABELLED projection on CPU
        # fallbacks (ROADMAP item 3's ≥0.6-of-peak is an on-chip claim).
        fused_projection = (
            fused_roofline_projection(last_onchip, log)
            if last_onchip
            else None
        )
        result = {
            "metric": metric,
            "value": round(rps, 2),
            "unit": "rounds/s",
            "vs_baseline": round(rps / baseline_rps, 1),
            "extra": {
                "platform": platform,
                # Correctness-tooling health rides every record (smoke
                # included): the perf number and the analyzer verdict
                # describe the same tree.
                **(analyzer_health(log) or {}),
                **({"tpu_note": tpu_note} if tpu_note else {}),
                **({"last_onchip": last_onchip} if last_onchip else {}),
                **(
                    {"roofline_fused_projection": fused_projection}
                    if fused_projection
                    else {}
                ),
                "rounds_to_convergence": converged_at,
                "baseline_kind": "extrapolated_python_object_model_estimate",
                "python_object_model_rounds_per_sec_est": round(baseline_rps, 4),
                "anchored_asyncio_3node_convergence_s": anchored,
                # The real reference library, measured live (64-node
                # loopback): both its test-interval behavior and its
                # compute-bound ceiling — the extrapolated vs_baseline
                # above now sits next to a measured datum.
                "measured_reference_library": ref_measured,
                # The asyncio fast path, floored-interval-free: pooled
                # persistent channels vs connect-per-round on the same
                # 64-node view (benchmarks/handshake_bench.py).
                "runtime_handshake_bench": hs_bench,
                # Reconvergence after a healed 3-way partition, both
                # backends, one seeded plan (benchmarks/fault_bench.py).
                "fault_bench": fault_rec,
                # Wrong-data tolerance: the (byz fraction x phi x
                # fanout) phase map, one compile (byzantine_bench.py).
                "byzantine_atlas": byz_rec,
                # S-lane sweep vs S sequential runs: lane-rounds/s and
                # the compile-amortization ratio (sweep_bench.py).
                "sweep_bench": sweep_rec,
                # 2-process multihost mesh, measured + parity-gated.
                "multihost_bench": mh_rec,
                # Serve tier: encode-once fan-out measured against a
                # per-request-encode control arm (serve_bench.py).
                "serve_bench": serve_rec,
                # Graceful degradation under storm + surge: layer
                # on-vs-off availability, breakers, adaptive p99
                # (overload_bench.py, docs/robustness.md).
                "overload_bench": overload_rec,
                # Durable node state: warm-vs-cold rejoin ratio, warm
                # reconvergence, leave-vs-phi detection, gate verdicts
                # (restart_bench.py, docs/robustness.md).
                "restart_bench": restart_rec,
                # Virtual-time runtime: compressed-clock compression
                # ratio, bit-identical seeded replay, long-horizon
                # scenario verdicts (vtime_bench.py, docs/virtual-time.md).
                "vtime_bench": vtime_rec,
                # Digital twin: calibrated rounds/s with held-out
                # validation error + the SLO autotuner's recommendation
                # (twin_bench.py, docs/twin.md).
                "twin_bench": twin_rec,
                # Propagation provenance: the marked write's measured
                # spread tree next to the sim wavefront prediction
                # (propagation_bench.py, docs/observability.md).
                "propagation_bench": prov_rec,
                # Fleet telemetry: any-member view coverage/staleness
                # through a split-brain heal + exact provenance joins
                # (fleet_bench.py, docs/observability.md).
                "fleet_bench": fleet_rec,
                # The memory ladder's planning claims (per-rung B/pair,
                # modeled max scale) — every entry certified: false
                # until the chip calibrates the new paths.
                "memory_ladder": memory_ladder_models(log),
                # Which PACKED rungs ride the in-place Pallas path
                # under this build's dispatch (u4r via the VMEM nibble
                # codec, shrunk/deep via the packed FD epilogue) — a
                # dispatch regression shows up as a record diff.
                "packed_kernel_engaged": packed_rung_engagement(log),
                # Round-4 flagship: the measured (mesh-certified) 100k
                # rounds-to-convergence + its v5e-8 projection.
                "northstar_100k": load_northstar_record(log),
                # Round-5: measured full-profile (heartbeats+FD) exact R
                # at the largest N walked, mesh-certification status.
                "full_profile_scale": load_full_profile_record(log),
                # Round-5: dynamic-workload (writes-under-gossip) data —
                # burst recovery + sustained staleness; the on-chip
                # battery phase supersedes the CPU record when it lands.
                "dynamic_workload": load_staleness_record(log),
                "keys_per_node": 16,
                "fanout": 3,
                "budget": _budget(),
                "budget_source": f"exact wire-size budget of the reference {_mtu_bytes()}B MTU",
                "failure_detector": True,
                "version_dtype": "int16",
                "heartbeat_dtype": "int16",
                "fd_dtype": "bfloat16",
                "max_scale_single_chip": (
                    {"nodes": SCALE_PROBE_N, "profile": "lean", "rounds_per_sec": probe_rps}
                    if probe_rps is not None
                    else None
                ),
                "max_scale_single_chip_measured_boundary": (
                    {
                        "nodes": probe_max_n,
                        "planner_limit_nodes": MAX_LEAN_SINGLE_CHIP,
                        "profile": "lean",
                        "rounds_per_sec": probe_max_rps,
                    }
                    if probe_max_rps is not None
                    else None
                ),
                # Planner verdict for the ceiling claim, with measured
                # provenance: "measured": false labels a number still
                # resting on the analytic model alone (round-3 lesson).
                "max_scale_planner_verdict": (
                    None
                    if args.smoke
                    else _planner_verdict_summary(log)
                ),
                **sim_extra,
            },
        }
        record_path = write_full_record(result, log)
        print(json.dumps(compact_record(result, record_path)), flush=True)
    except Exception as exc:
        # One diagnosable JSON line even on failure (round-1 lesson).
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": None,
                    "unit": "rounds/s",
                    "vs_baseline": None,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            ),
            flush=True,
        )
        raise


if __name__ == "__main__":
    main()
