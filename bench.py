"""Benchmark: batched TPU gossip simulation vs the pure-Python object model.

Headline metric (BASELINE.md): simulated gossip rounds/second at 10k nodes
(BASELINE config 4 scale) on one chip, full failure-detector fidelity.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is the measured speed of the equivalent pure-Python gossip round —
the reference's own execution model — extrapolated to the same cluster
size: per-handshake cost is fit as t(N) = a + b*N over in-memory engine
handshakes (digest size grows with N), and a full round costs
N * fanout * t(N). The ratio is therefore "how many times faster one
process simulates the cluster than the asyncio object model could".

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.

Usage: python bench.py [--smoke] [--nodes N] [--rounds R]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def measure_python_handshake_seconds(n_nodes: int) -> float:
    """Mean wall-clock of one full in-memory 3-way handshake between two
    nodes of an ``n_nodes``-sized cluster view (object model, no sockets)."""
    from datetime import UTC, datetime

    from aiocluster_tpu.core import (
        ClusterState,
        Config,
        FailureDetector,
        FailureDetectorConfig,
        NodeId,
    )
    from aiocluster_tpu.runtime.engine import GossipEngine
    from aiocluster_tpu.wire import decode_packet, encode_packet

    ts = datetime(2026, 1, 1, tzinfo=UTC)
    nodes = [NodeId(f"n{i}", i + 1, ("h", i + 1)) for i in range(n_nodes)]

    def build_engine(self_idx: int, know_all: bool) -> GossipEngine:
        cfg = Config(node_id=nodes[self_idx], cluster_id="bench")
        cs = ClusterState()
        fd = FailureDetector(FailureDetectorConfig())
        population = nodes if know_all else [nodes[self_idx]]
        for k, node in enumerate(population):
            ns = cs.node_state_or_default(node)
            ns.heartbeat = 5
            for j in range(16):
                ns.set_with_version(f"key-{j:04d}", f"v{k}:{j}", j + 1, ts=ts)
        return GossipEngine(cfg, cs, fd)

    # One side knows the cluster, the other is missing a couple of nodes'
    # latest keys — the steady-state shape of a real round.
    a = build_engine(0, know_all=True)
    b = build_engine(1, know_all=True)
    for i in range(2, 5):
        ns = b._state.node_state_or_default(nodes[i])
        ns.set_with_version("fresh", "x", 17, ts=ts)

    reps = 5
    start = time.perf_counter()
    for _ in range(reps):
        syn = decode_packet(encode_packet(a.make_syn()))
        synack = decode_packet(encode_packet(b.handle_syn(syn)))
        ack = decode_packet(encode_packet(a.handle_synack(synack)))
        b.handle_ack(ack)
    return (time.perf_counter() - start) / reps


def python_rounds_per_sec(n_target: int) -> float:
    """Extrapolated whole-cluster rounds/sec for the object model."""
    n1, n2 = 128, 512
    t1 = measure_python_handshake_seconds(n1)
    t2 = measure_python_handshake_seconds(n2)
    b = max((t2 - t1) / (n2 - n1), 0.0)
    a = max(t1 - b * n1, 1e-9)
    t_target = a + b * n_target
    fanout = 3
    round_time = n_target * fanout * t_target
    return 1.0 / round_time


BUDGET = 2048  # key-versions per exchange ~ 64KB MTU / ~30B per kv update


def sim_rounds_per_sec(n_nodes: int, rounds: int, log) -> tuple[float, int | None]:
    import jax
    import numpy as np

    from aiocluster_tpu.sim import SimConfig, Simulator

    # int16 knowledge matrices: exact for this workload (versions ≤ 16,
    # horizon ≪ 32768 ticks — see SimConfig.version_dtype) and half the
    # HBM traffic of int32, which is what the round time is made of.
    cfg = SimConfig(
        n_nodes=n_nodes,
        keys_per_node=16,
        fanout=3,
        budget=BUDGET,
        version_dtype="int16",
        heartbeat_dtype="int16",
        fd_dtype="bfloat16",
    )
    sim = Simulator(cfg, seed=0, chunk=min(rounds, 16))
    log(f"devices: {jax.devices()}")

    def sync() -> int:
        # block_until_ready does not reliably block through the axon
        # tunnel; a scalar device->host readback provably does.
        return int(np.asarray(sim.state.tick))

    # Warm-up: compile + first chunk.
    t0 = time.perf_counter()
    sim.run(sim.chunk)
    sync()
    log(f"compile+first chunk: {time.perf_counter() - t0:.1f}s")

    # The tunnel to the TPU is shared and noisy; take the best of three
    # trials as the device's attainable rate.
    rps = 0.0
    for trial in range(3):
        start = time.perf_counter()
        sim.run(rounds)
        end_tick = sync()
        elapsed = time.perf_counter() - start
        rps = max(rps, rounds / elapsed)
        log(
            f"trial {trial}: {rounds} rounds in {elapsed:.2f}s "
            f"-> {rounds / elapsed:.1f} rounds/s (tick={end_tick})"
        )

    # Convergence from a FRESH cluster (the timing runs above have long
    # converged this one).
    t0 = time.perf_counter()
    fresh = Simulator(cfg, seed=1, chunk=sim.chunk)
    # Cap the horizon inside the int16 heartbeat/tick contract (< 2^15).
    converged_at = fresh.run_until_converged(
        max_rounds=min(4 * n_nodes, 30_000)
    )
    log(
        f"rounds to full convergence @ {n_nodes} nodes: {converged_at} "
        f"({time.perf_counter() - t0:.1f}s wall)"
    )
    return rps, converged_at


def scale_probe(log, n_nodes: int = 32_768, rounds: int = 16) -> float:
    """Max single-chip scale: the lean convergence profile (int16
    watermarks, no FD matrices — sim/memory.py) at the largest N that fits
    one chip's HBM. The 100k-node north star runs this profile sharded
    over a v5e-8 (BASELINE.md config 5); this records the per-chip rate
    the projection is built on."""
    import numpy as np

    from aiocluster_tpu.sim import Simulator
    from aiocluster_tpu.sim.memory import lean_config, plan

    cfg = lean_config(n_nodes)
    assert plan(cfg).fits(), "probe config must fit one chip"
    sim = Simulator(cfg, seed=0, chunk=8)
    t0 = time.perf_counter()
    sim.run(8)
    int(np.asarray(sim.state.tick))
    log(f"scale probe compile+first chunk: {time.perf_counter() - t0:.1f}s")
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        sim.run(rounds)
        int(np.asarray(sim.state.tick))
        best = max(best, rounds / (time.perf_counter() - t0))
    log(f"scale probe @ {n_nodes} nodes (lean): {best:.1f} rounds/s")
    return best


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="small CPU-friendly run")
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--rounds", type=int, default=None)
    args = parser.parse_args()

    n_nodes = args.nodes or (512 if args.smoke else 10_000)
    rounds = args.rounds or (32 if args.smoke else 64)

    def log(msg: str) -> None:
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    # int16 heartbeat contract: warmup + 3 timed trials must stay < 2^15
    # ticks (SimConfig.heartbeat_dtype).
    if rounds > 10_000:
        log(f"--rounds {rounds} capped to 10000 (int16 tick horizon)")
        rounds = 10_000

    rps, converged_at = sim_rounds_per_sec(n_nodes, rounds, log)
    baseline_rps = python_rounds_per_sec(n_nodes)
    log(f"python object-model estimate: {baseline_rps:.4f} rounds/s")
    probe_rps = None
    if not args.smoke:
        try:
            probe_rps = round(scale_probe(log), 2)
        except Exception as exc:  # keep the headline even if the probe dies
            log(f"scale probe failed: {exc!r}")
    result = {
        "metric": f"sim_gossip_rounds_per_sec@{n_nodes}_nodes",
        "value": round(rps, 2),
        "unit": "rounds/s",
        "vs_baseline": round(rps / baseline_rps, 1),
        "extra": {
            "rounds_to_convergence": converged_at,
            "python_object_model_rounds_per_sec_est": round(baseline_rps, 4),
            "keys_per_node": 16,
            "fanout": 3,
            "budget": BUDGET,
            "failure_detector": True,
            "version_dtype": "int16",
            "heartbeat_dtype": "int16",
            "fd_dtype": "bfloat16",
            "max_scale_single_chip": (
                {"nodes": 32_768, "profile": "lean", "rounds_per_sec": probe_rps}
                if probe_rps is not None
                else None
            ),
        },
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
